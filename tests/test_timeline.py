"""Step-timeline attribution (observability/timeline + its wiring
through the profiler, the trainer, the serving engine, heartbeats, and
the Perfetto exporter).

The PR's load-bearing acceptance criteria, pinned here:

- on the COMMITTED trace fixture the bucket fractions are
  deterministic, sum to 1.0 ± 1e-6 of the step window, and the
  overlapped collective is attributed as overlapped while the exposed
  one lands in ``timeline_exposed_collective_seconds`` — CPU-only;
- with ``profile_every`` on, ``n_traces`` stays pinned at 1, profiled
  steps stay out of the step-time series (the PR 9 invariant), and the
  measured non-sample-step overhead stays bounded;
- a comm-heavy straggler gets a ``comm_bound`` cause label in the
  coordinator's aggregated health report;
- flight-recorder evictions are counted and stamped into dumps;
- registry snapshots carry a build stamp.
"""

import json
import os
import time

import numpy as np
import pytest

from singa_tpu import profiling as prof
from singa_tpu.observability import (metrics, perf, spans, timeline,
                                     trace_export)

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "data", "trace_fixture")


@pytest.fixture
def reg():
    return metrics.MetricsRegistry()


@pytest.fixture(autouse=True)
def _clean_recorder():
    spans.recorder().clear()
    yield
    spans.recorder().clear()
    spans.recorder().detach_jsonl()


# ---------------------------------------------------------------------------
# classification + interval math (unit)
# ---------------------------------------------------------------------------

class TestClassify:
    @pytest.mark.parametrize("name, bucket", [
        ("fusion.1", "compute"),
        ("fusion.1|convolution.3", "compute"),
        ("dot_general.5", "compute"),
        ("all-reduce.1", "collective"),
        ("all-reduce-start.2", "collective"),
        ("all-gather.3", "collective"),
        ("reduce-scatter.7", "collective"),
        ("all-to-all.1", "collective"),
        ("collective-permute.4", "collective"),
        ("fusion.9|all-reduce.2", "collective"),   # enriched symbol
        ("send.1", "collective"),
        ("recv-done.1", "collective"),
        ("infeed.7", "memcpy"),
        ("outfeed.2", "memcpy"),
        ("copy.4", "memcpy"),
        ("copy-start.1", "memcpy"),
        ("copy-done.9", "memcpy"),
        ("MemcpyD2H", "memcpy"),
        ("TransferToDevice", "memcpy"),
    ])
    def test_buckets(self, name, bucket):
        assert timeline.classify_op(name) == bucket


class TestIntervals:
    def test_merge(self):
        assert timeline.merge_intervals(
            [(5, 15), (0, 10), (20, 30), (30, 31)]) == \
            [(0.0, 15.0), (20.0, 31.0)]
        assert timeline.merge_intervals([]) == []
        assert timeline.merge_intervals([(5, 5)]) == []   # empty iv

    def test_subtract(self):
        assert timeline.subtract_intervals(
            [(0, 10)], [(3, 5)]) == [(0, 3), (5, 10)]
        assert timeline.subtract_intervals(
            [(0, 10), (20, 30)], [(5, 25)]) == [(0, 5), (25, 30)]
        assert timeline.subtract_intervals([(0, 10)], []) == [(0, 10)]
        assert timeline.subtract_intervals([(0, 10)], [(0, 10)]) == []

    def test_intersect(self):
        assert timeline.intersect_intervals(
            [(0, 10), (20, 30)], [(5, 25)]) == [(5, 10), (20, 25)]
        assert timeline.intersect_intervals([(0, 10)], [(10, 20)]) == []


# ---------------------------------------------------------------------------
# the committed fixture: deterministic CPU-only decomposition
# ---------------------------------------------------------------------------

class TestFixtureDecomposition:
    """Fixture layout (µs): compute fusion.1 [0,120)+[130,160),
    dot_general.5 [170,220); all-reduce.1 [20,100) fully OVERLAPPED by
    compute; all-gather.3 [220,260) EXPOSED; infeed.7 [260,280);
    host-lane TransferHostToDevice [280,340); window (0,400)."""

    def _analyze(self, window=(0, 400)):
        events = prof.parse_trace_events(FIXTURE)
        return timeline.analyze(events, window=window)

    def test_fractions_deterministic_and_partition(self):
        tl = self._analyze()
        assert tl["fractions"] == {
            "compute": pytest.approx(0.5),
            "collective": pytest.approx(0.1),
            "memcpy": pytest.approx(0.05),
            "host": pytest.approx(0.15),
            "idle": pytest.approx(0.2)}
        # acceptance: an exact partition of the step window
        assert sum(tl["fractions"].values()) == pytest.approx(
            1.0, abs=1e-6)
        assert tl["window_s"] == pytest.approx(400e-6)

    def test_overlapped_vs_exposed_collective(self):
        """The all-reduce under compute is free; the all-gather in the
        gap is the exposed-communication bill."""
        tl = self._analyze()
        assert tl["collective_s"] == pytest.approx(120e-6)
        assert tl["exposed_collective_s"] == pytest.approx(40e-6)
        assert tl["overlapped_collective_s"] == pytest.approx(80e-6)

    def test_host_vs_idle_gap_split(self):
        """A gap where the HOST lane is busy is a host stall; a gap
        where nothing runs anywhere is idle."""
        tl = self._analyze()
        assert tl["host_s"] == pytest.approx(60e-6)
        assert tl["idle_s"] == pytest.approx(80e-6)

    def test_default_window_spans_device_ops(self):
        tl = self._analyze(window=None)
        assert tl["window_s"] == pytest.approx(280e-6)
        assert sum(tl["fractions"].values()) == pytest.approx(
            1.0, abs=1e-6)

    def test_lanes_are_bounded_relative_intervals(self):
        tl = self._analyze()
        assert tl["lanes"]["collective"] == [
            [pytest.approx(20e-6), pytest.approx(80e-6)],
            [pytest.approx(220e-6), pytest.approx(40e-6)]]
        assert tl["lanes"]["host"] == [
            [pytest.approx(280e-6), pytest.approx(60e-6)]]
        for ivs in tl["lanes"].values():
            assert len(ivs) <= 128

    def test_empty_and_unplaceable_events(self):
        assert timeline.analyze([]) is None
        assert timeline.analyze(
            [{"name": "f", "ts": None, "dur": 5, "lane": "device",
              "xla_op": True}]) is None

    def test_cpu_fallback_uses_host_xla_ops(self):
        """No device lanes (CPU CI): host XLA-op events become the op
        timeline; runtime frames are excluded and the host bucket is
        empty (indistinguishable from compute there)."""
        evs = [
            {"name": "fusion.1", "ts": 0, "dur": 50, "lane": "host",
             "xla_op": True},
            {"name": "all-reduce.1", "ts": 60, "dur": 40,
             "lane": "host", "xla_op": True},
            {"name": "PjRtCpuExecutable::Execute", "ts": 0, "dur": 100,
             "lane": "host", "xla_op": False},
        ]
        tl = timeline.analyze(evs)
        assert tl["window_s"] == pytest.approx(100e-6)
        assert tl["fractions"]["compute"] == pytest.approx(0.5)
        assert tl["fractions"]["collective"] == pytest.approx(0.4)
        assert tl["fractions"]["idle"] == pytest.approx(0.1)
        assert tl["fractions"]["host"] == 0.0


class TestWaterfall:
    def test_attributes_the_gap(self):
        tl = timeline.analyze(prof.parse_trace_events(FIXTURE),
                              window=(0, 400))
        # 1e6 flops over 400µs against a 1e10 peak: achieved 0.25
        wf = timeline.waterfall(tl, step_flops=1e6, peak_flops=1e10)
        assert wf["achieved_mfu"] == pytest.approx(0.25)
        assert wf["loss"] == {
            "collective": pytest.approx(0.1),
            "memcpy": pytest.approx(0.05),
            "host": pytest.approx(0.15),
            "idle": pytest.approx(0.2),
            "compute_inefficiency": pytest.approx(0.25)}
        # achieved + every loss = 1.0: the waterfall closes
        assert wf["achieved_mfu"] + sum(wf["loss"].values()) == \
            pytest.approx(1.0)

    def test_unknown_flops_is_none(self):
        tl = timeline.analyze(prof.parse_trace_events(FIXTURE))
        assert timeline.waterfall(tl, None, 1e10) is None
        assert timeline.waterfall(tl, 1e6, None) is None
        assert timeline.waterfall(None, 1e6, 1e10) is None


# ---------------------------------------------------------------------------
# gauge publication + heartbeat readback
# ---------------------------------------------------------------------------

class TestRecordTimeline:
    def test_gauges_and_summary_roundtrip(self, reg):
        tl = timeline.analyze(prof.parse_trace_events(FIXTURE),
                              window=(0, 400))
        wf = timeline.waterfall(tl, 1e6, 1e10)
        timeline.record_timeline(tl, registry=reg, site="train",
                                 waterfall_doc=wf)
        g = reg.get("timeline_fraction")
        assert g.value(site="train", bucket="compute") == \
            pytest.approx(0.5)
        assert g.value(site="train", bucket="collective") == \
            pytest.approx(0.1)
        assert reg.get("timeline_exposed_collective_seconds").value(
            site="train") == pytest.approx(40e-6)
        assert reg.get("timeline_collective_total_seconds").value(
            site="train") == pytest.approx(120e-6)
        assert reg.get("timeline_mfu").value(site="train") == \
            pytest.approx(0.25)
        assert reg.get("timeline_mfu_loss").value(
            site="train", bucket="compute_inefficiency") == \
            pytest.approx(0.25)
        # the heartbeat-compact readback
        s = timeline.timeline_summary(reg, site="train")
        assert s["fractions"]["idle"] == pytest.approx(0.2)
        assert s["exposed_collective_s"] == pytest.approx(40e-6)
        assert s["window_s"] == pytest.approx(400e-6)
        # a site nobody recorded reads as None, not zeros
        assert timeline.timeline_summary(reg, site="serve") is None

    def test_empty_registry_summary_is_none(self, reg):
        assert timeline.timeline_summary(reg) is None


class TestClassifyCause:
    def test_comm_bound(self):
        assert timeline.classify_cause(
            {"compute": 0.4, "collective": 0.4, "memcpy": 0.0,
             "host": 0.1, "idle": 0.1}) == "comm_bound"

    def test_data_bound(self):
        assert timeline.classify_cause(
            {"compute": 0.5, "collective": 0.05, "memcpy": 0.1,
             "host": 0.2, "idle": 0.15}) == "data_bound"

    def test_compute_bound(self):
        assert timeline.classify_cause(
            {"compute": 0.9, "collective": 0.02, "memcpy": 0.02,
             "host": 0.03, "idle": 0.03}) == "compute_bound"

    def test_compile_bound_wins(self):
        """A retracing rank also looks idle on the device timeline —
        the compile share is checked FIRST."""
        assert timeline.classify_cause(
            {"compute": 0.1, "collective": 0.0, "memcpy": 0.0,
             "host": 0.0, "idle": 0.9},
            compile_share=0.6) == "compile_bound"

    def test_nothing_to_judge(self):
        assert timeline.classify_cause(None) is None
        assert timeline.classify_cause({}, compile_share=0.1) == \
            "compute_bound"


class TestStragglerCauses:
    @staticmethod
    def _rank(mean, count=20, **extra):
        return dict({"step_time": {"count": count, "sum": mean * count,
                                   "min": mean, "max": mean,
                                   "mean": mean},
                     "wire_errors": 0}, **extra)

    def test_comm_bound_straggler_labeled(self):
        """Acceptance: the slow rank's own heartbeat carried a
        comm-heavy timeline — the aggregated fleet view labels it
        comm_bound (and the straggler list itself is unchanged)."""
        comm_heavy = {"fractions": {
            "compute": 0.4, "collective": 0.45, "memcpy": 0.0,
            "host": 0.05, "idle": 0.1}, "exposed_collective_s": 0.02}
        agg = metrics.aggregate_summaries({
            0: self._rank(0.010), 1: self._rank(0.011),
            2: self._rank(0.050, timeline=comm_heavy),
            3: self._rank(0.012)})
        assert agg["step_time_stragglers"] == [2]
        assert agg["straggler_causes"] == {"2": "comm_bound"}

    def test_data_and_compile_bound_labels(self):
        agg = metrics.aggregate_summaries({
            0: self._rank(0.010),
            1: self._rank(0.050, timeline={"fractions": {
                "compute": 0.5, "collective": 0.0, "memcpy": 0.05,
                "host": 0.25, "idle": 0.2}}),
            2: self._rank(0.060, compile_share=0.7),
            3: self._rank(0.010)})
        assert sorted(agg["step_time_stragglers"]) == [1, 2]
        assert agg["straggler_causes"] == {
            "1": "data_bound", "2": "compile_bound"}

    def test_straggler_without_timeline_is_unknown(self):
        agg = metrics.aggregate_summaries(
            {0: self._rank(0.010), 1: self._rank(0.011),
             2: self._rank(0.050)})
        assert agg["straggler_causes"] == {"2": "unknown"}

    def test_no_stragglers_no_causes(self):
        agg = metrics.aggregate_summaries(
            {0: self._rank(0.010), 1: self._rank(0.011)})
        assert agg["step_time_stragglers"] == []
        assert "straggler_causes" not in agg


class TestHeartbeatCarriesTimeline:
    def test_timeline_and_build_ride_the_summary(self, reg):
        reg.histogram("train_step_seconds").observe(0.1)
        tl = timeline.analyze(prof.parse_trace_events(FIXTURE),
                              window=(0, 400))
        timeline.record_timeline(tl, registry=reg, site="train")
        s = metrics.heartbeat_summary(reg)
        assert s["timeline"]["fractions"]["collective"] == \
            pytest.approx(0.1)
        assert s["timeline"]["exposed_collective_s"] == \
            pytest.approx(40e-6)
        assert "start_ts" in s["build"] and "git" in s["build"]

    def test_compile_share_rides_when_observed(self, reg):
        reg.histogram("train_step_seconds").observe(1.0)
        reg.histogram("compile_seconds",
                      labels=("program", "source")).observe(
            0.5, program="train_step", source="fresh")
        s = metrics.heartbeat_summary(reg)
        assert s["compile_share"] == pytest.approx(0.5)

    def test_summary_without_samples_has_no_timeline(self, reg):
        s = metrics.heartbeat_summary(reg)
        assert "timeline" not in s and "compile_share" not in s


# ---------------------------------------------------------------------------
# build stamp in snapshots
# ---------------------------------------------------------------------------

class TestBuildStamp:
    def test_snapshot_carries_build(self, reg):
        snap = reg.snapshot()
        b = snap["build"]
        assert b["pid"] == os.getpid()
        assert b["start_ts"] <= time.time()
        assert "git" in b and "host" in b
        # stable across calls (cached), and JSON-able
        assert metrics.build_stamp() == metrics.build_stamp()
        json.dumps(snap)

    def test_snapshot_still_validates_and_renders(self, reg):
        from singa_tpu.observability import export
        reg.counter("x_total").inc()
        export.validate_snapshot(reg.snapshot())
        assert "x_total" in export.render_prometheus(reg.snapshot())


# ---------------------------------------------------------------------------
# flight-recorder eviction visibility
# ---------------------------------------------------------------------------

class TestRecorderEvictions:
    def test_evictions_counted_and_stamped_in_dump(self, tmp_path,
                                                   reg):
        before = metrics.default_registry().counter(
            "recorder_evicted_total").value()
        rec = spans.FlightRecorder(capacity=4)
        for i in range(10):
            rec.record({"kind": "event", "name": f"e{i}",
                        "ts": float(i)})
        assert rec.dropped_records == 6
        assert metrics.default_registry().counter(
            "recorder_evicted_total").value() == before + 6
        path = rec.dump(str(tmp_path / "bb.jsonl"), reason="test",
                        registry=reg)
        with open(path) as f:
            head = json.loads(f.readline())
        assert head["dropped_records"] == 6
        assert head["ring_capacity"] == 4

    def test_no_evictions_dump_says_zero(self, tmp_path, reg):
        rec = spans.FlightRecorder(capacity=16)
        rec.record({"kind": "event", "name": "only", "ts": 1.0})
        path = rec.dump(str(tmp_path / "bb.jsonl"), reason="test",
                        registry=reg)
        head = json.loads(open(path).readline())
        assert head["dropped_records"] == 0

    def test_live_records_carry_partiality_marker(self, reg):
        rec = spans.FlightRecorder(capacity=2)
        for i in range(5):
            rec.record({"kind": "event", "name": f"e{i}",
                        "ts": float(i)})
        recs = trace_export.live_records(recorder=rec, registry=reg)
        (marker,) = [r for r in recs
                     if r.get("name") == "recorder.dropped"]
        assert marker["dropped_records"] == 3
        # and a full ring leaves no marker
        rec2 = spans.FlightRecorder(capacity=8)
        rec2.record({"kind": "event", "name": "e", "ts": 1.0})
        assert not [r for r in trace_export.live_records(
            recorder=rec2, registry=reg)
            if r.get("name") == "recorder.dropped"]

    def test_configure_shrink_counts_dropped(self):
        rec = spans.recorder()
        for i in range(8):
            spans.event(f"e{i}")
        before = rec.dropped_records
        counter_before = metrics.default_registry().counter(
            "recorder_evicted_total").value()
        spans.configure(capacity=2)
        try:
            assert rec.dropped_records >= before + 6
            # header total and metrics counter move in lockstep — a
            # dashboard alerting on the counter must see the shrink
            assert metrics.default_registry().counter(
                "recorder_evicted_total").value() >= \
                counter_before + 6
        finally:
            spans.configure(capacity=spans.DEFAULT_CAPACITY)


# ---------------------------------------------------------------------------
# Perfetto: timeline lanes
# ---------------------------------------------------------------------------

class TestTimelineLanes:
    def _sample_event(self):
        return {
            "kind": "event", "name": "timeline.sample", "rank": 0,
            "ts": 10.0, "step": 5, "site": "train",
            "window_s": 0.0004,
            "fractions": {"compute": 0.5, "collective": 0.1,
                          "memcpy": 0.05, "host": 0.15, "idle": 0.2},
            "exposed_collective_s": 4e-5,
            "lanes": {
                "compute": [[0.0, 0.00012], [0.00013, 3e-05],
                            [0.00017, 5e-05]],
                "collective": [[2e-05, 8e-05], [0.00022, 4e-05]],
                "memcpy": [[0.00026, 2e-05]],
                "host": [[0.00028, 6e-05]],
                "idle": [[0.00012, 1e-05], [0.00016, 1e-05],
                         [0.00034, 6e-05]]}}

    def test_lanes_render_as_named_rows(self):
        doc = trace_export.to_chrome_trace(
            [{"kind": "span", "name": "step", "rank": 0, "ts": 10.0,
              "ts_start": 9.999, "dur_s": 0.001},
             self._sample_event()])
        trace_export.validate_chrome_trace(doc)
        lanes = {e["args"]["name"]: (e["pid"], e["tid"])
                 for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "thread_name"
                 and e["args"]["name"].startswith("timeline ")}
        assert set(lanes) == {"timeline compute", "timeline collective",
                              "timeline memcpy", "timeline host",
                              "timeline idle"}
        coll = [e for e in doc["traceEvents"]
                if e.get("cat") == "timeline"
                and e["name"] == "collective"]
        assert len(coll) == 2
        assert coll[1]["dur"] == pytest.approx(40.0)    # 4e-5 s in µs
        # the two collective intervals keep their relative offset
        assert coll[1]["ts"] - coll[0]["ts"] == pytest.approx(200.0)
        # the instant event survives WITHOUT the raw interval list
        (inst,) = [e for e in doc["traceEvents"]
                   if e["name"] == "timeline.sample"]
        assert "lanes" not in inst["args"]
        assert inst["args"]["fractions"]["compute"] == 0.5

    def test_sample_without_lanes_is_plain_event(self):
        ev = self._sample_event()
        del ev["lanes"]
        doc = trace_export.to_chrome_trace([ev])
        trace_export.validate_chrome_trace(doc)
        assert not [e for e in doc["traceEvents"]
                    if e.get("cat") == "timeline"]


# ---------------------------------------------------------------------------
# trainer wiring: gauges refresh, series exclusion, overhead
# ---------------------------------------------------------------------------

class TestTrainerTimeline:
    def _compiled_mlp(self, batch=16):
        from singa_tpu import device, layer, model, opt, tensor

        class MLP(model.Model):
            def __init__(self):
                super().__init__()
                self.fc1 = layer.Linear(16)
                self.relu = layer.ReLU()
                self.fc2 = layer.Linear(4)
                self.loss_fn = layer.SoftMaxCrossEntropy()

            def forward(self, x):
                return self.fc2(self.relu(self.fc1(x)))

            def train_one_batch(self, x, y):
                out = self.forward(x)
                loss = self.loss_fn(out, y)
                self.optimizer(loss)
                return out, loss

        dev = device.create_cpu_device()
        dev.SetRandSeed(7)
        rng = np.random.RandomState(0)
        x = rng.randn(batch, 8).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, batch)]
        tx = tensor.Tensor(data=x, device=dev, requires_grad=False)
        ty = tensor.Tensor(data=y, device=dev, requires_grad=False)
        m = MLP()
        m.set_optimizer(opt.SGD(lr=0.1, momentum=0.9))
        m.compile([tx], is_train=True, use_graph=True)
        return m, tx, ty

    def test_profile_every_refreshes_timeline_gauges(self, tmp_path):
        """Acceptance (training half): profile_every=2 on — timeline_*
        gauges refresh continuously, fractions partition the window,
        n_traces stays 1, and timeline.sample events carry the lanes
        the exporter renders."""
        from singa_tpu.resilience import ResilientTrainer
        reg = metrics.default_registry()
        m, tx, ty = self._compiled_mlp()
        tr = ResilientTrainer(m, str(tmp_path / "run"),
                              save_interval_steps=3, verbose=False,
                              profile_every=2)
        try:
            s = tr.run([(tx, ty)], num_steps=6)
        finally:
            tr.close()
        assert s["steps_run"] == 6
        assert m.compiled_step_info()["n_traces"] == 1
        g = reg.get("timeline_fraction")
        assert g is not None
        fr = {b: g.value(site="train", bucket=b)
              for b in timeline.BUCKETS}
        assert sum(fr.values()) == pytest.approx(1.0, abs=1e-6)
        assert fr["compute"] > 0            # the MLP computed SOMETHING
        assert reg.get("timeline_window_seconds").value(
            site="train") > 0
        # exposed-comm exists as a series even on a single CPU device
        assert reg.get(
            "timeline_exposed_collective_seconds") is not None
        samples = [r for r in spans.recorder().records()
                   if r["name"] == "timeline.sample"]
        assert samples and samples[-1]["site"] == "train"
        assert samples[-1]["lanes"]["compute"]
        # the profiler kept the newest decomposition for callers
        assert tr._profiler.last_timeline is not None

    def test_non_sample_overhead_still_bounded(self, reg):
        """The timeline work rides ONLY the sampled step: a non-sample
        step still pays one integer check (PR 9's bound, re-measured
        with the timeline layer present)."""
        profiler = perf.SamplingProfiler(every=1000, registry=reg)
        n = 300
        t0 = time.perf_counter()
        for i in range(n):
            profiler.should_sample(i)
        per_step = (time.perf_counter() - t0) / n
        assert per_step < 200e-6, f"{per_step * 1e6:.1f} µs per step"

    def test_profiler_record_without_events_unchanged(self, reg):
        """A caller that passes no events (bench probes, older call
        sites) gets the PR-9 behavior: fusion gauges only, no timeline
        series created."""
        p = perf.SamplingProfiler(every=2, registry=reg)
        p.record(4, {"fusion.1": (1, 0.001)}, capture_s=0.01)
        assert reg.get("timeline_fraction") is None
        assert p.last_timeline is None


# ---------------------------------------------------------------------------
# serving: profiled decode tick
# ---------------------------------------------------------------------------

class TestServingProfiledTick:
    def _tiny_engine(self, **kw):
        from singa_tpu import device, tensor
        from singa_tpu.models import transformer
        dev = device.create_cpu_device()
        np.random.seed(0)
        m = transformer.TransformerLM(19, d_model=16, n_heads=2,
                                      n_layers=2, max_len=64, tp=False)
        m.eval()
        m(tensor.Tensor(data=np.zeros((1, 4), np.float32), device=dev,
                        requires_grad=False))
        return m.compile_serving(slots=2, max_len=32, prefill_len=8,
                                 registry=metrics.MetricsRegistry(),
                                 **kw)

    def test_profiled_tick_records_serve_timeline(self):
        """Acceptance (serving half): every Nth tick profiled — the
        decode program still traced exactly once, the profiled ticks
        stayed out of the SLO latency series, and the engine's registry
        carries the site=serve decomposition."""
        eng = self._tiny_engine(profile_every=3)
        rng = np.random.RandomState(0)
        futs = [eng.submit(rng.randint(1, 19, (3,)), max_new_tokens=6)
                for _ in range(6)]
        eng.run_until_idle()
        for f in futs:
            f.result(timeout=5)
        assert eng.compiled_step_info()["n_traces"] == 1
        reg = eng._reg
        samples = reg.get("serve_profile_samples_total").value()
        assert samples >= 1
        assert reg.get(
            "serve_profile_capture_seconds").summary()["count"] == \
            samples
        # profiled ticks are excluded from the per-token SLO series
        decode_ticks = reg.get("serve_decode_steps_total").value()
        observed = reg.get("serve_token_seconds").summary()["count"]
        assert observed < decode_ticks
        # the decomposition landed (CPU host-fallback lanes)
        assert eng.last_timeline is not None
        g = reg.get("timeline_fraction")
        fr = {b: g.value(site="serve", bucket=b)
              for b in timeline.BUCKETS}
        assert sum(fr.values()) == pytest.approx(1.0, abs=1e-6)
        eng.stop()

    def test_profile_every_off_changes_nothing(self):
        eng = self._tiny_engine()
        fut = eng.submit([1, 2, 3], max_new_tokens=3)
        eng.run_until_idle()
        fut.result(timeout=5)
        assert eng._reg.get("serve_profile_samples_total") is None
        assert eng.last_timeline is None
        eng.stop()

    def test_gateway_serves_timeline_json(self):
        import urllib.request

        from singa_tpu.serving import serve_gateway
        eng = self._tiny_engine(profile_every=2).start()
        server, port = serve_gateway(eng)
        try:
            body = json.dumps({"prompt": [1, 2, 3],
                               "max_new_tokens": 8}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/generate", data=body,
                headers={"Content-Type": "application/json"})
            doc = json.loads(urllib.request.urlopen(
                req, timeout=30).read())
            assert doc["tokens"]
            tl = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/timeline.json",
                timeout=30).read())
            assert tl["site"] == "serve"
            if tl["timeline"] is not None:      # ≥1 profiled tick ran
                assert "lanes" not in tl["timeline"]
                assert sum(tl["timeline"]["fractions"].values()) == \
                    pytest.approx(1.0, abs=1e-6)
        finally:
            server.shutdown()
            server.server_close()
            eng.stop()


# ---------------------------------------------------------------------------
# cluster end-to-end: the comm-bound straggler in the health report
# ---------------------------------------------------------------------------

class TestClusterCauseLabels:
    """In-process coordinator+workers (the test_cluster pattern): the
    slow rank's heartbeat carries a comm-heavy timeline, and the
    coordinator's aggregated health report names it comm_bound."""

    def _spawn(self, world):
        import socket
        import threading

        from singa_tpu.resilience.cluster import (ClusterConfig,
                                                  make_cluster)
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        addr = f"127.0.0.1:{port}"
        cfg = ClusterConfig(heartbeat_interval=0.1,
                            straggler_after=0.3, dead_after=1.0,
                            connect_timeout=10.0)
        members = [None] * world
        members[0] = make_cluster(0, world, addr, cfg)

        def up(r):
            members[r] = make_cluster(r, world, addr, cfg)

        ts = [threading.Thread(target=up, args=(r,))
              for r in range(1, world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(15)
        assert all(m is not None for m in members)
        return members

    @staticmethod
    def _source(mean, count=20, timeline_doc=None):
        def src():
            s = {"step_time": {"count": count, "sum": mean * count,
                               "min": mean, "max": mean, "mean": mean},
                 "wire_errors": 0}
            if timeline_doc is not None:
                s["timeline"] = timeline_doc
            return s
        return src

    def test_comm_heavy_straggler_labeled_in_health(self):
        from singa_tpu import network as net
        if not net.available():
            pytest.skip("native network layer unavailable")
        members = self._spawn(3)
        try:
            comm_heavy = {"fractions": {
                "compute": 0.35, "collective": 0.45, "memcpy": 0.0,
                "host": 0.05, "idle": 0.15},
                "exposed_collective_s": 0.02}
            members[0].metrics_source = self._source(0.010)
            members[1].metrics_source = self._source(0.011)
            members[2].metrics_source = self._source(
                0.060, timeline_doc=comm_heavy)
            # wait until every rank's POST-injection summary landed
            # (the first beats carry whatever the process registry
            # held — 3 ranks × 20 steps marks the injected set)
            deadline = time.monotonic() + 8
            agg = None
            while time.monotonic() < deadline:
                agg = members[0].health().get("worker_metrics") or {}
                if agg.get("steps") == 60:
                    break
                time.sleep(0.05)
            assert agg.get("step_time_stragglers") == [2], agg
            assert agg.get("straggler_causes") == {"2": "comm_bound"}, \
                agg
            # workers see the cause-labeled view on hb-ack too
            deadline = time.monotonic() + 8
            wagg = None
            while time.monotonic() < deadline:
                wagg = members[1].health().get("worker_metrics") or {}
                if wagg.get("steps") == 60:
                    break
                time.sleep(0.05)
            assert wagg.get("straggler_causes") == \
                {"2": "comm_bound"}, wagg
        finally:
            for m in members:
                try:
                    m.close()
                except Exception:
                    pass
