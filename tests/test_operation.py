"""Every autograd op: forward vs a numpy/jax oracle, backward vs jax.grad
of the oracle (the reference checks each op against numpy the same way,
test/python/test_operation.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from singa_tpu import autograd, tensor
from singa_tpu.tensor import Tensor


def t(arr, rg=True):
    return Tensor(data=np.asarray(arr, dtype=np.float32),
                  requires_grad=rg, stores_grad=rg)


def check(op_fn, ref_fn, *arrays, rtol=1e-5, atol=1e-6, grad=True,
          grad_args=None):
    """Forward parity + gradient parity against jax.grad of the oracle.

    ``grad_args`` limits which inputs' gradients are compared (losses
    stop-gradient their target, matching the reference)."""
    autograd.training = True
    try:
        ts = [t(a) for a in arrays]
        y = op_fn(*ts)
        ref = ref_fn(*[jnp.asarray(a, jnp.float32) for a in arrays])
        np.testing.assert_allclose(np.asarray(y.data), np.asarray(ref),
                                   rtol=rtol, atol=atol)
        if not grad:
            return
        if grad_args is None:
            grad_args = tuple(range(len(arrays)))
        grads = {id(p): g for p, g in autograd.backward(y)}
        ref_grads = jax.grad(
            lambda *xs: jnp.sum(ref_fn(*xs)),
            argnums=tuple(grad_args))(
                *[jnp.asarray(a, jnp.float32) for a in arrays])
        for i, rg_ in zip(grad_args, ref_grads):
            tt = ts[i]
            assert id(tt) in grads, "missing grad"
            np.testing.assert_allclose(np.asarray(grads[id(tt)].data),
                                       np.asarray(rg_), rtol=rtol, atol=atol)
    finally:
        autograd.training = False


A = np.random.RandomState(3).randn(4, 5).astype(np.float32)
B = np.random.RandomState(4).randn(4, 5).astype(np.float32)
P = np.abs(A) + 0.5  # positive operand


class TestArithmetic:
    def test_add(self):
        check(autograd.add, jnp.add, A, B)

    def test_sub(self):
        check(autograd.sub, jnp.subtract, A, B)

    def test_mul(self):
        check(autograd.mul, jnp.multiply, A, B)

    def test_div(self):
        check(autograd.div, jnp.divide, A, P)

    def test_pow(self):
        check(autograd.pow, jnp.power, P, B)

    def test_negative(self):
        check(autograd.negative, jnp.negative, A)

    def test_reciprocal(self):
        check(autograd.reciprocal, lambda x: 1.0 / x, P)

    def test_matmul(self):
        check(autograd.matmul, jnp.matmul, A, B.T)

    def test_gemm(self):
        check(lambda a, b, c: autograd.gemm(a, b, c, alpha=2.0, beta=3.0,
                                            transA=0, transB=1),
              lambda a, b, c: 2.0 * (a @ b.T) + 3.0 * c, A, B,
              np.ones((4, 4), np.float32))

    def test_sum_nary(self):
        check(autograd.sum, lambda a, b, c: a + b + c, A, B, A)

    def test_add_bias(self):
        b = np.random.randn(5).astype(np.float32)
        check(lambda x, bb: autograd.add_bias(x, bb, axis=0),
              lambda x, bb: x + bb[None, :], A, b)


class TestUnaryMath:
    @pytest.mark.parametrize("name,ref,arg", [
        ("abs", jnp.abs, A), ("exp", jnp.exp, A), ("log", jnp.log, P),
        ("sqrt", jnp.sqrt, P), ("sin", jnp.sin, A), ("cos", jnp.cos, A),
        ("tan", jnp.tan, A * 0.3), ("sinh", jnp.sinh, A),
        ("cosh", jnp.cosh, A), ("tanh", jnp.tanh, A),
        ("asin", jnp.arcsin, A * 0.19), ("acos", jnp.arccos, A * 0.19),
        ("atan", jnp.arctan, A), ("asinh", jnp.arcsinh, A),
        ("acosh", jnp.arccosh, P + 1.0), ("atanh", jnp.arctanh, A * 0.19),
        ("erf", jax.scipy.special.erf, A),
    ])
    def test_fn(self, name, ref, arg):
        check(getattr(autograd, name), ref, arg, rtol=2e-5, atol=2e-5)

    def test_rounding_zero_grad(self):
        autograd.training = True
        try:
            for fn in (autograd.ceil, autograd.floor, autograd.sign,
                       autograd.rounde):
                x = t(A)
                y = fn(x)
                grads = {id(p): g for p, g in autograd.backward(y)}
                np.testing.assert_array_equal(
                    np.asarray(grads[id(x)].data), np.zeros_like(A))
        finally:
            autograd.training = False

    def test_round_half_away(self):
        x = np.array([0.5, -0.5, 1.5, 2.4, -2.5], np.float32)
        y = autograd.round(t(x, rg=False))
        np.testing.assert_array_equal(np.asarray(y.data),
                                      [1.0, -1.0, 2.0, 2.0, -3.0])


class TestActivations:
    def test_relu(self):
        check(autograd.relu, lambda x: jnp.maximum(x, 0), A)

    def test_leakyrelu(self):
        check(lambda x: autograd.leakyrelu(x, 0.1),
              lambda x: jnp.where(x >= 0, x, 0.1 * x), A)

    def test_elu(self):
        check(lambda x: autograd.elu(x, 1.5),
              lambda x: jnp.where(x > 0, x, 1.5 * (jnp.exp(x) - 1)), A)

    def test_selu(self):
        a, g = 1.67326, 1.0507
        check(autograd.selu,
              lambda x: g * jnp.where(x > 0, x, a * (jnp.exp(x) - 1)), A)

    def test_sigmoid(self):
        check(autograd.sigmoid, jax.nn.sigmoid, A)

    def test_softplus(self):
        check(autograd.softplus, jax.nn.softplus, A)

    def test_softsign(self):
        check(autograd.softsign, lambda x: x / (1 + jnp.abs(x)), A)

    def test_hardsigmoid(self):
        check(autograd.hardsigmoid,
              lambda x: jnp.clip(0.2 * x + 0.5, 0, 1), A)

    def test_prelu(self):
        s = np.full((5,), 0.25, np.float32)
        check(autograd.prelu,
              lambda x, sl: jnp.where(x >= 0, x, sl * x), A, s)

    def test_softmax(self):
        check(lambda x: autograd.softmax(x, axis=1),
              lambda x: jax.nn.softmax(x, axis=1), A)

    def test_gelu(self):
        check(autograd.gelu, jax.nn.gelu, A, rtol=1e-4)


class TestLosses:
    def test_softmax_cross_entropy_onehot(self):
        logits = np.random.RandomState(0).randn(6, 4).astype(np.float32)
        target = np.eye(4, dtype=np.float32)[[0, 1, 2, 3, 1, 2]]
        check(autograd.softmax_cross_entropy,
              lambda x, tt: jnp.mean(-jnp.sum(
                  tt * jax.nn.log_softmax(x, -1), -1)),
              logits, target, grad_args=(0,))

    def test_cross_entropy(self):
        p = np.random.RandomState(1).rand(6, 4).astype(np.float32)
        p /= p.sum(1, keepdims=True)
        target = np.eye(4, dtype=np.float32)[[0, 1, 2, 3, 1, 2]]
        check(autograd.cross_entropy,
              lambda x, tt: -jnp.sum(tt * jnp.log(x + 1e-10)) / x.shape[0],
              p, target, grad_args=(0,))

    def test_mse(self):
        check(autograd.mse_loss,
              lambda x, tt: jnp.sum((x - tt) ** 2) / (2 * x.shape[0]),
              A, B, grad_args=(0,))

    def test_bce(self):
        p = np.random.RandomState(1).rand(6, 4).astype(np.float32)
        q = (np.random.RandomState(2).rand(6, 4) > 0.5).astype(np.float32)
        check(autograd.binary_cross_entropy,
              lambda x, tt: jnp.mean(jnp.sum(
                  -(tt * jnp.log(x + 1e-10) +
                    (1 - tt) * jnp.log(1 - x + 1e-10)), -1)), p, q,
              grad_args=(0,))

    def test_ranking(self):
        pos = np.random.RandomState(5).rand(8).astype(np.float32)
        neg = np.random.RandomState(6).rand(8).astype(np.float32)
        check(lambda p_, n_: autograd.ranking_loss(p_, n_, M=0.3),
              lambda p_, n_: jnp.mean(jnp.maximum(0.3 - (p_ - n_), 0)),
              pos, neg)


class TestReductions:
    def test_reduce_sum(self):
        check(lambda x: autograd.reduce_sum(x, axes=[1], keepdims=0),
              lambda x: jnp.sum(x, axis=1), A)

    def test_reduce_mean(self):
        check(lambda x: autograd.reduce_mean(x, axes=[0], keepdims=1),
              lambda x: jnp.mean(x, axis=0, keepdims=True), A)

    def test_mean_nary(self):
        check(autograd.mean, lambda a, b: (a + b) / 2, A, B)

    def test_max_min(self):
        check(autograd.max, jnp.maximum, A, B)
        check(autograd.min, jnp.minimum, A, B)

    def test_clip(self):
        check(lambda x: autograd.clip(x, -0.5, 0.5),
              lambda x: jnp.clip(x, -0.5, 0.5), A)

    def test_comparisons(self):
        for fn, ref in [(autograd.less, jnp.less),
                        (autograd.greater, jnp.greater),
                        (autograd.equal, jnp.equal)]:
            y = fn(t(A, rg=False), t(B, rg=False))
            np.testing.assert_array_equal(
                np.asarray(y.data), np.asarray(ref(A, B), np.float32))


class TestShapeOps:
    def test_reshape(self):
        check(lambda x: autograd.reshape(x, (5, 4)),
              lambda x: jnp.reshape(x, (5, 4)), A)

    def test_flatten(self):
        x3 = np.random.randn(2, 3, 4).astype(np.float32)
        check(lambda x: autograd.flatten(x, axis=1),
              lambda x: jnp.reshape(x, (2, 12)), x3)

    def test_transpose(self):
        check(lambda x: autograd.transpose(x, (1, 0)), lambda x: x.T, A)

    def test_squeeze_unsqueeze(self):
        x = np.random.randn(1, 4, 1, 5).astype(np.float32)
        check(lambda v: autograd.squeeze(v, (0, 2)),
              lambda v: jnp.squeeze(v, (0, 2)), x)
        check(lambda v: autograd.unsqueeze(v, [0, 2]),
              lambda v: jnp.expand_dims(jnp.expand_dims(v, 0), 2), A)

    def test_cat(self):
        autograd.training = True
        try:
            a, b = t(A), t(B)
            y = autograd.cat([a, b], axis=0)
            np.testing.assert_allclose(np.asarray(y.data),
                                       np.concatenate([A, B], 0))
            grads = {id(p): g for p, g in autograd.backward(y)}
            assert np.asarray(grads[id(a)].data).shape == A.shape
        finally:
            autograd.training = False

    def test_split(self):
        autograd.training = True
        try:
            a = t(A)
            y1, y2 = autograd.split(a, axis=1, parts=[2, 3])
            np.testing.assert_allclose(np.asarray(y1.data), A[:, :2])
            np.testing.assert_allclose(np.asarray(y2.data), A[:, 2:])
        finally:
            autograd.training = False

    def test_slice(self):
        check(lambda x: autograd.slice(x, [1], [3], [0]),
              lambda x: x[1:3], A)

    def test_gather(self):
        idx = np.array([0, 2], np.int32)
        check(lambda x: autograd.gather(x, 1, idx),
              lambda x: jnp.take(x, jnp.asarray(idx), axis=1), A)

    def test_tile(self):
        check(lambda x: autograd.tile(x, [2, 1]),
              lambda x: jnp.tile(x, (2, 1)), A)

    def test_pad(self):
        check(lambda x: autograd.pad(x, "constant", [1, 0, 0, 2], 1.5),
              lambda x: jnp.pad(x, ((1, 0), (0, 2)), constant_values=1.5), A)

    def test_upsample(self):
        x = np.random.randn(1, 2, 3, 3).astype(np.float32)
        check(lambda v: autograd.upsample(v, "nearest", [1, 1, 2, 2]),
              lambda v: jnp.repeat(jnp.repeat(v, 2, 2), 2, 3), x)

    def test_depth_space_roundtrip(self):
        x = np.random.randn(2, 8, 3, 3).astype(np.float32)
        y = autograd.depth_to_space(t(x, rg=False), 2)
        z = autograd.space_to_depth(y, 2)
        np.testing.assert_allclose(np.asarray(z.data), x)

    def test_expand(self):
        x = np.random.randn(1, 5).astype(np.float32)
        check(lambda v: autograd.expand(v, (4, 5)),
              lambda v: jnp.broadcast_to(v, (4, 5)), x)


class TestIndexing:
    def test_where(self):
        cond = (A > 0).astype(np.float32)
        check(lambda a, b: autograd.where(t(cond, rg=False), a, b),
              lambda a, b: jnp.where(jnp.asarray(cond) > 0, a, b), A, B)

    def test_onehot(self):
        idx = np.array([0, 2, 1], np.float32)
        y = autograd.onehot(-1, t(idx, rg=False), 3)
        np.testing.assert_array_equal(np.asarray(y.data), np.eye(3)[[0, 2, 1]])

    def test_embedding(self):
        W = np.random.randn(7, 3).astype(np.float32)
        ids = np.array([1, 4, 6], np.float32)
        autograd.training = True
        try:
            w = t(W)
            y = autograd.embedding(t(ids, rg=False), w)
            np.testing.assert_allclose(np.asarray(y.data), W[[1, 4, 6]])
            grads = {id(p): g for p, g in autograd.backward(y)}
            gw = np.asarray(grads[id(w)].data)
            assert gw[1].sum() == 3.0 and gw[0].sum() == 0.0
        finally:
            autograd.training = False

    def test_cossim(self):
        check(autograd.cossim,
              lambda a, b: jnp.sum(a * b, -1) /
              (jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1)
               + 1e-12), A, B, rtol=1e-4)

    def test_shape_cast_identity(self):
        y = autograd.shape(t(A, rg=False))
        np.testing.assert_array_equal(np.asarray(y.data), [4, 5])
        y = autograd.cast(t(A, rg=False), jnp.int32)
        assert y.data.dtype == jnp.int32
        check(autograd.identity, lambda x: x, A)

    def test_scatter_elements(self):
        x = np.zeros((3, 3), np.float32)
        idx = np.array([[0, 1, 2]], np.float32)
        upd = np.array([[1.0, 2.0, 3.0]], np.float32)
        y = autograd.scatter_elements(t(x, rg=False), t(idx, rg=False),
                                      t(upd, rg=False), axis=0)
        expect = np.zeros((3, 3), np.float32)
        expect[0, 0], expect[1, 1], expect[2, 2] = 1, 2, 3
        np.testing.assert_array_equal(np.asarray(y.data), expect)


class TestDropout:
    def test_eval_passthrough(self):
        autograd.training = False
        y = autograd.dropout(t(A, rg=False), 0.5)
        np.testing.assert_array_equal(np.asarray(y.data), A)

    def test_train_scales(self):
        autograd.training = True
        try:
            x = np.ones((1000,), np.float32)
            y = autograd.dropout(t(x), 0.4)
            vals = np.asarray(y.data)
            kept = vals[vals != 0]
            np.testing.assert_allclose(kept, 1.0 / 0.6, rtol=1e-5)
            assert 0.45 < (vals != 0).mean() < 0.75
        finally:
            autograd.training = False


class TestBroadcastHelpers:
    """Reference autograd.axis_helper/back_broadcast (autograd.py:34/52)."""

    def test_axis_helper_matches_reference_semantics(self):
        from singa_tpu.autograd import axis_helper
        assert axis_helper((4, 3, 5), (3, 5)) == (0,)
        assert axis_helper((4, 3, 5), (1, 5)) == (0, 1)
        assert axis_helper((4, 3, 5), (5,)) == (0, 1)
        assert axis_helper((2, 2), (2, 2)) == ()

    def test_back_broadcast_sums_to_shape(self):
        import numpy as np
        from singa_tpu.autograd import back_broadcast
        from singa_tpu.tensor import Tensor
        y = np.ones((4, 3, 5), np.float32)
        got = back_broadcast((4, 3, 5), (1, 5), y)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.full((1, 5), 12.0))
        t = Tensor(data=y)
        got_t = back_broadcast((4, 3, 5), (3, 5), t)
        assert isinstance(got_t, Tensor)
        np.testing.assert_array_equal(got_t.numpy(),
                                      np.full((3, 5), 4.0))
