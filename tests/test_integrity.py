"""End-to-end integrity: content digests, wire CRCs, replica
fingerprints — the full corruption matrix.

Disk: a bit-flipped / tampered checkpoint shard is detected at restore
and the fallback chain (peer shards, then older steps) lands on a
verified step; Snapshot/BinFile records verify against their digest
sidecars. Wire: a corrupted control-plane frame raises a typed
IntegrityError, is dropped-and-counted by the cluster loops, and never
reaches protocol parsing; the hello handshake rejects version-
mismatched peers by name. Replicas: bit-exact fingerprints disagree on
silent divergence, the trainer quarantines + rolls back to the last
cluster-agreed checkpoint, and repeated divergence raises the
EXIT_DIVERGED contract.
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from singa_tpu import device, layer, model, opt, tensor
from singa_tpu.integrity import (IntegrityError, manifest_digest,
                                 open_frame, replica_buffer_mismatches,
                                 seal_frame, state_fingerprint,
                                 tensor_digest, verify_tree)


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

class TestDigestPrimitives:
    def test_tensor_digest_covers_bytes_dtype_and_shape(self):
        a = np.arange(12, dtype=np.float32)
        assert tensor_digest(a) == tensor_digest(a.copy())
        b = a.copy()
        b.view(np.int32)[7] ^= 1          # ONE flipped mantissa bit
        assert tensor_digest(b) != tensor_digest(a)
        assert tensor_digest(a.reshape(3, 4)) != tensor_digest(a)
        assert tensor_digest(a.astype(np.float64).astype(np.float32)) \
            == tensor_digest(a)
        assert tensor_digest(a.view(np.int32)) != tensor_digest(a)

    def test_manifest_digest_is_order_independent(self):
        d1 = {"a": "crc32:1:4", "b": "crc32:2:4"}
        d2 = dict(reversed(list(d1.items())))
        assert manifest_digest(d1) == manifest_digest(d2)
        assert manifest_digest(d1) != manifest_digest(
            {**d1, "c": "crc32:3:4"})

    def test_verify_tree_flags_mismatch_and_missing(self):
        a = np.arange(4, dtype=np.float32)
        digests = {"x": tensor_digest(a), "y": tensor_digest(a)}
        assert verify_tree({"x": a, "y": a}, digests) == []
        assert verify_tree({"x": a + 1, "y": a}, digests) == ["x"]
        # a digested entry missing from the arrays is corruption too
        assert verify_tree({"x": a}, digests) == ["y"]
        # extra arrays without a digest are additive state, not errors
        assert verify_tree({"x": a, "y": a, "z": a}, digests) == []


class TestWireFraming:
    def test_seal_open_roundtrip(self):
        meta, payload = b"kind", b'{"a": 1}'
        assert open_frame(meta, seal_frame(meta, payload)) == payload
        assert open_frame(b"", seal_frame(b"", b"")) == b""

    @pytest.mark.parametrize("mutate,excerpt", [
        (lambda s: s[:10], "truncated"),
        (lambda s: b"XXXX" + s[4:], "magic"),
        (lambda s: s[:4] + bytes([99]) + s[5:], "version"),
        (lambda s: s[:-1] + bytes([s[-1] ^ 1]), "CRC"),
        (lambda s: s + b"junk", "length"),
    ])
    def test_every_corruption_is_typed_and_named(self, mutate, excerpt):
        sealed = seal_frame(b"kind", b"payload-bytes")
        with pytest.raises(IntegrityError, match=excerpt):
            open_frame(b"kind", mutate(sealed))

    def test_meta_corruption_detected_too(self):
        sealed = seal_frame(b"kind", b"payload")
        with pytest.raises(IntegrityError, match="metadata"):
            open_frame(b"kinX", sealed)


# ---------------------------------------------------------------------------
# network layer
# ---------------------------------------------------------------------------

net = pytest.importorskip("singa_tpu.network")
if not net.available():
    pytest.skip("native network layer unavailable", allow_module_level=True)


def _loopback():
    srv = net.NetworkThread(port=0)
    cli = net.NetworkThread(port=-1)
    ep = cli.connect("127.0.0.1", srv.port)
    peer = srv.accept(timeout=5.0)
    assert peer is not None
    return srv, cli, ep, peer


class TestSealedEndpoints:
    def test_sealed_roundtrip_and_corruption_raises(self):
        srv, cli, ep, peer = _loopback()
        try:
            ep.send_sealed(net.Message(b"m", b"payload"))
            got = peer.recv_sealed(timeout=5.0)
            assert (got.meta, got.payload) == (b"m", b"payload")
            # a bit flips on the wire: typed error, never garbage
            msg = net.Message(b"m", seal_frame(b"m", b"payload"))
            msg.payload = msg.payload[:-1] + \
                bytes([msg.payload[-1] ^ 0x01])
            ep.send(msg)
            with pytest.raises(IntegrityError):
                peer.recv_sealed(timeout=5.0)
            # the endpoint survives: later traffic still flows
            ep.send_sealed(net.Message(b"m2", b"after"))
            assert peer.recv_sealed(timeout=5.0).payload == b"after"
        finally:
            cli.close()
            srv.close()

    def test_oversized_frame_guard_consumes_and_raises(self):
        srv, cli, ep, peer = _loopback()
        try:
            ep.send(net.Message(b"m", b"x" * 100))
            with pytest.raises(IntegrityError, match="oversized"):
                peer.recv(timeout=5.0, max_bytes=64)
            # the poisoned frame was consumed — the link still works;
            # and an UNcapped recv (the general message layer) is not
            # subject to the control-plane limit
            ep.send(net.Message(b"m", b"y" * 100))
            assert peer.recv(timeout=5.0).payload == b"y" * 100
        finally:
            cli.close()
            srv.close()


# ---------------------------------------------------------------------------
# cluster: corrupt frames dropped, versioned hello, fp agreement
# ---------------------------------------------------------------------------

from singa_tpu.resilience import FaultPlan                   # noqa: E402
from singa_tpu.resilience.cluster import (ClusterConfig,     # noqa: E402
                                          make_cluster)

FAST = ClusterConfig(heartbeat_interval=0.05, straggler_after=0.2,
                     dead_after=10.0, connect_timeout=10.0)


def _coordinator_addr():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    addr = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    return addr


def _pair(worker_faults=None):
    addr = _coordinator_addr()
    members = [None, None]
    members[0] = make_cluster(0, 2, addr, FAST)

    def bring_up():
        members[1] = make_cluster(1, 2, addr, FAST,
                                  faults=worker_faults)

    t = threading.Thread(target=bring_up)
    t.start()
    t.join(20)
    assert members[1] is not None
    return members


class TestClusterWireIntegrity:
    def test_corrupt_heartbeat_dropped_counted_and_survived(self):
        # heartbeats 3 and 4 are sent bit-flipped (seq 1 = hello, so
        # the handshake stays clean); the coordinator must drop them,
        # count them, and keep the cluster healthy
        faults = FaultPlan().corrupt_wire(3, times=2)
        members = _pair(worker_faults=faults)
        try:
            with pytest.warns(UserWarning, match="corrupt"):
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline and \
                        members[0].wire_errors < 2:
                    time.sleep(0.05)
            assert members[0].wire_errors == 2
            assert members[0].health()["wire_errors"] == 2
            # the protocol survives corruption: barriers still complete
            done = []
            t = threading.Thread(target=lambda: done.append(
                members[1].barrier("after-noise", timeout=10)))
            t.start()
            members[0].barrier("after-noise", timeout=10)
            t.join(10)
            assert len(done) == 1
            assert members[0].health()["dead"] == []
        finally:
            for m in members:
                m.close()

    def test_hello_version_negotiation_rejects_by_name(self):
        # a well-formed sealed hello announcing a FUTURE protocol
        # version: the coordinator must reject it naming both versions
        from singa_tpu.resilience.cluster import _msg
        addr = _coordinator_addr()
        coord = make_cluster(0, 2, addr, FAST)
        cli = net.NetworkThread(port=-1)
        try:
            host, port = addr.rsplit(":", 1)
            ep = cli.connect(host, int(port))
            with pytest.warns(UserWarning, match="protocol version 99"):
                ep.send(_msg("hello", rank=1, proto=99))
                reply = ep.recv_sealed(timeout=5.0)
            assert reply is not None and reply.meta == b"hello-reject"
            data = json.loads(reply.payload.decode())
            assert "protocol version 99" in data["reason"]
            assert data["proto"] == 1     # the version this side speaks
        finally:
            cli.close()
            coord.close()

    def test_unsealed_hello_rejected(self):
        # a pre-integrity (or garbage-speaking) peer: its raw hello
        # cannot unseal — the coordinator must turn it away, not parse
        addr = _coordinator_addr()
        coord = make_cluster(0, 2, addr, FAST)
        cli = net.NetworkThread(port=-1)
        try:
            host, port = addr.rsplit(":", 1)
            ep = cli.connect(host, int(port))
            with pytest.warns(UserWarning, match="corrupt"):
                ep.send(net.Message(b"hello", b'{"rank": 1}'))
                reply = ep.recv_sealed(timeout=5.0)
            assert reply is not None and reply.meta == b"hello-reject"
            assert "unreadable hello" in json.loads(
                reply.payload.decode())["reason"]
        finally:
            cli.close()
            coord.close()

    def test_fingerprint_agreement_and_divergence_named(self):
        members = _pair()
        try:
            out = [None, None]

            def worker(seq, fp):
                out[1] = members[1].fingerprint_agree(seq, fp,
                                                      timeout=10)

            # round 1: agreement
            t = threading.Thread(target=worker, args=(1, "fp-same"))
            t.start()
            out[0] = members[0].fingerprint_agree(1, "fp-same",
                                                  timeout=10)
            t.join(10)
            assert out == [(True, []), (True, [])]
            # round 2: rank 1 diverges and is NAMED on both sides
            t = threading.Thread(target=worker, args=(2, "fp-forked"))
            t.start()
            with pytest.warns(UserWarning, match="DISAGREEMENT"):
                out[0] = members[0].fingerprint_agree(2, "fp-true",
                                                      timeout=10)
            t.join(10)
            # 1-vs-1 cannot attribute blame (majority-vote tie): the
            # guarantee is a CONSISTENT not-ok verdict on both sides,
            # with exactly one side named
            assert out[0] == out[1]
            ok, divergent = out[0]
            assert ok is False and len(divergent) == 1
        finally:
            for m in members:
                m.close()

    def test_ack_digest_disagreement_aborts_commit(self):
        committed = []
        members = _pair()
        members[0].set_commit_hook(lambda step: committed.append(step))
        try:
            with pytest.warns(UserWarning, match="digests disagree"):
                members[1].ack_save(7, digest="crc32:aaaaaaaa:2")
                members[0].ack_save(7, digest="crc32:bbbbbbbb:2")
                ok = members[0].wait_commit(7, timeout=10)
            assert ok is False
            assert committed == []    # the hook never ran: no marker
            assert members[1].wait_commit(7, timeout=10) is False
        finally:
            for m in members:
                m.close()


# ---------------------------------------------------------------------------
# snapshot + record-file digests
# ---------------------------------------------------------------------------

class TestSnapshotDigests:
    def _states(self):
        return {"w": np.arange(24, dtype=np.float32).reshape(4, 6),
                "b": np.ones(6, np.float32),
                "step": np.asarray([3], np.int32)}

    def test_roundtrip_writes_and_verifies_sidecar(self, tmp_path):
        from singa_tpu.snapshot import load_states, save_states
        prefix = str(tmp_path / "snap")
        save_states(prefix, self._states())
        assert os.path.exists(prefix + ".digest")
        got = load_states(prefix)
        np.testing.assert_array_equal(got["w"].numpy(),
                                      self._states()["w"])

    def test_bitflip_in_bin_raises_named_record(self, tmp_path):
        from singa_tpu.snapshot import Snapshot, save_states
        prefix = str(tmp_path / "snap")
        save_states(prefix, self._states())
        # flip ONE bit inside the record data (the singa BinFile has no
        # checksum of its own — only the digest layer can catch this)
        path = prefix + ".bin"
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0x01]))
        with pytest.raises(IntegrityError, match="failed its content"):
            Snapshot(prefix, Snapshot.kRead).read()
        # verify=False restores the old trusting behavior explicitly
        Snapshot(prefix, Snapshot.kRead).read(verify=False)

    def test_missing_sidecar_loads_unverified(self, tmp_path):
        from singa_tpu.snapshot import load_states, save_states
        prefix = str(tmp_path / "snap")
        save_states(prefix, self._states())
        os.remove(prefix + ".digest")     # e.g. a real SINGA checkpoint
        assert set(load_states(prefix)) == set(self._states())


class TestRecordFileDigests:
    def test_verify_roundtrip_corruption_and_truncation(self, tmp_path):
        from singa_tpu.io import (BinFileReader, BinFileWriter,
                                  verify_record_file)
        path = str(tmp_path / "data.bin")
        with BinFileWriter(path, digest=True) as w:
            for i in range(5):
                w.Write(f"k{i}", os.urandom(64))
        assert verify_record_file(path) == 5
        # reader-integrated verification
        r = BinFileReader(path, verify=True)
        r.Close()
        # corrupt one record body
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0x01]))
        with pytest.raises(IntegrityError, match="failed its content"):
            verify_record_file(path)

    def test_bytes_keys_are_verified_not_skipped(self, tmp_path):
        """Bytes keys (the native writer accepts them) must land in the
        sidecar under the same name the verifier computes — a naming
        mismatch would silently skip exactly the records it covers."""
        from singa_tpu.io import (BinFileWriter, IntegrityError,
                                  verify_record_file)
        path = str(tmp_path / "bk.bin")
        with BinFileWriter(path, digest=True) as w:
            w.Write(b"bytes-key", b"payload")
        assert verify_record_file(path) == 1
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.seek(size - 2)
            b = f.read(1)
            f.seek(size - 2)
            f.write(bytes([b[0] ^ 0x01]))
        with pytest.raises(IntegrityError, match="failed its content"):
            verify_record_file(path)

    def test_append_continues_the_sidecar(self, tmp_path):
        from singa_tpu.io import BinFileWriter, verify_record_file
        path = str(tmp_path / "app.bin")
        with BinFileWriter(path, digest=True) as w:
            w.Write("a", b"1")
            w.Write("b", b"2")
        with BinFileWriter(path, mode="append", digest=True) as w:
            w.Write("c", b"3")
        assert verify_record_file(path) == 3    # healthy after append
        # appending with digests onto an undigested file is refused
        plain = str(tmp_path / "plain.bin")
        with BinFileWriter(plain) as w:
            w.Write("a", b"1")
        with pytest.raises(ValueError, match="digest=True"):
            BinFileWriter(plain, mode="append", digest=True)

    def test_no_sidecar_is_a_clear_error(self, tmp_path):
        from singa_tpu.io import BinFileWriter, verify_record_file
        path = str(tmp_path / "plain.bin")
        with BinFileWriter(path) as w:      # digest=False: no sidecar
            w.Write("k", b"v")
        with pytest.raises(FileNotFoundError):
            verify_record_file(path)


# ---------------------------------------------------------------------------
# checkpoint digests: verify-on-restore, fallback, scrub
# ---------------------------------------------------------------------------

class MLP(model.Model):
    def __init__(self):
        super().__init__()
        self.fc1 = layer.Linear(8)
        self.relu = layer.ReLU()
        self.fc2 = layer.Linear(4)
        self.loss_fn = layer.SoftMaxCrossEntropy()

    def forward(self, x):
        return self.fc2(self.relu(self.fc1(x)))

    def train_one_batch(self, x, y):
        out = self.forward(x)
        loss = self.loss_fn(out, y)
        self.optimizer(loss)
        return out, loss


def _compiled_mlp(seed=7):
    dev = device.create_cpu_device()
    dev.SetRandSeed(seed)
    rng = np.random.RandomState(0)
    x = rng.randn(8, 6).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 8)]
    tx = tensor.Tensor(data=x, device=dev, requires_grad=False)
    ty = tensor.Tensor(data=y, device=dev, requires_grad=False)
    m = MLP()
    m.set_optimizer(opt.SGD(lr=0.1, momentum=0.9))
    m.compile([tx], is_train=True, use_graph=True)
    return m, tx, ty


def _tamper_digest(mgr, step, entry=None):
    """Rewrite one record of a step's digest sidecar — equivalent to
    the DATA having changed under an honest sidecar, which is how a
    digest mismatch presents regardless of which side rotted."""
    path = mgr._digest_path(step)
    with open(path) as f:
        doc = json.load(f)
    key = entry or sorted(doc["records"])[0]
    doc["records"][key] = "crc32:deadbeef:4"
    with open(path, "w") as f:
        json.dump(doc, f)
    return key


class TestCheckpointDigests:
    def test_sidecars_written_and_rotated(self, tmp_path):
        from singa_tpu.checkpoint import CheckpointManager
        m, tx, ty = _compiled_mlp()
        mgr = CheckpointManager(tmp_path / "d", max_to_keep=2)
        try:
            for s in range(4):
                m(tx, ty)
                mgr.save(s, m)
                mgr.wait()
            kept = sorted(int(n[:-5]) for n in
                          os.listdir(tmp_path / "d" / "digests"))
            assert kept == mgr.all_steps() == [2, 3]
        finally:
            mgr.close()

    def test_digest_mismatch_falls_back_to_verified_step(self, tmp_path):
        from singa_tpu.checkpoint import CheckpointManager
        m, tx, ty = _compiled_mlp()
        mgr = CheckpointManager(tmp_path / "d", max_to_keep=4)
        states = {}
        try:
            for s in range(2):
                m(tx, ty)
                mgr.save(s, m)
                mgr.wait()
                states[s] = {k: np.asarray(t.data)
                             for k, t in m.get_states().items()}
            _tamper_digest(mgr, 1)
            m2, _, _ = _compiled_mlp(seed=99)
            with pytest.warns(UserWarning, match="digest mismatch"):
                assert mgr.restore_latest(m2) == 1   # fell back to 0
            got = {k: np.asarray(t.data)
                   for k, t in m2.get_states().items()}
            for k in got:        # bit-identical to the VERIFIED step
                np.testing.assert_array_equal(got[k], states[0][k],
                                              err_msg=k)
        finally:
            mgr.close()

    def test_scrub_reports_and_demotes(self, tmp_path):
        from singa_tpu.checkpoint import CheckpointManager
        m, tx, ty = _compiled_mlp()
        mgr = CheckpointManager(tmp_path / "d", max_to_keep=4)
        try:
            for s in range(3):
                m(tx, ty)
                mgr.save(s, m)
                mgr.wait()
            assert mgr.scrub() == {0: "ok", 1: "ok", 2: "ok"}
            _tamper_digest(mgr, 2)
            with pytest.warns(UserWarning, match="FAILED digest"):
                assert mgr.scrub()[2] == "corrupt"
            with pytest.warns(UserWarning, match="demoted"):
                mgr.scrub(delete=True)
            # rotation now only counts verified steps
            assert mgr.all_steps() == [0, 1]
            assert mgr.scrub() == {0: "ok", 1: "ok"}
        finally:
            mgr.close()

    def test_background_scrubber_reports(self, tmp_path):
        from singa_tpu.checkpoint import CheckpointManager
        m, tx, ty = _compiled_mlp()
        mgr = CheckpointManager(tmp_path / "d")
        try:
            for s in range(2):
                m(tx, ty)
                mgr.save(s, m)
                mgr.wait()
            _tamper_digest(mgr, 1)
            mgr.start_scrubber(interval=0.05)
            deadline = time.monotonic() + 20
            with pytest.warns(UserWarning, match="FAILED digest"):
                while time.monotonic() < deadline and not mgr.scrub_report:
                    time.sleep(0.05)
            assert mgr.scrub_report == {0: "ok", 1: "corrupt"}
        finally:
            mgr.close()          # also stops the scrubber

    def test_scrub_cli_detects_distributed_layout(self, tmp_path):
        from singa_tpu.checkpoint import DistributedCheckpointManager
        from singa_tpu.resilience.cluster import SoloCluster
        import importlib.util as ilu
        spec = ilu.spec_from_file_location(
            "scrub_checkpoints",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
                "tools", "scrub_checkpoints.py"))
        scrub_cli = ilu.module_from_spec(spec)
        spec.loader.exec_module(scrub_cli)

        m, tx, ty = _compiled_mlp()
        mgr = DistributedCheckpointManager(tmp_path / "d", SoloCluster(0))
        try:
            for s in range(2):
                m(tx, ty)
                mgr.save(s, m)
        finally:
            mgr.close()
        report = scrub_cli.scrub_root(str(tmp_path / "d"))
        assert report == {"rank0": {0: "ok", 1: "ok"}}

    def test_marker_carries_agreed_manifest_digest(self, tmp_path):
        from singa_tpu.checkpoint import DistributedCheckpointManager
        from singa_tpu.integrity import manifest_digest
        from singa_tpu.resilience.cluster import SoloCluster
        m, tx, ty = _compiled_mlp()
        mgr = DistributedCheckpointManager(tmp_path / "d", SoloCluster(0))
        try:
            m(tx, ty)
            assert mgr.save(0, m) is True
            man = mgr.read_manifest(0)
            assert man["digest"] == manifest_digest(
                mgr.read_digests(0)["records"])
        finally:
            mgr.close()

    def test_lost_sidecar_reverifies_against_marker_digest(self, tmp_path):
        """A shard whose sidecar is gone (lost, or its write failed at
        save time) is verified DIRECTLY against the cluster-committed
        manifest digest — a healthy shard restores (no crash loop), a
        content mismatch still fails to the fallback chain."""
        from singa_tpu.checkpoint import DistributedCheckpointManager
        from singa_tpu.resilience.cluster import SoloCluster
        m, tx, ty = _compiled_mlp()
        mgr = DistributedCheckpointManager(tmp_path / "d", SoloCluster(0))
        states = {}
        try:
            for s in range(2):
                m(tx, ty)
                assert mgr.save(s, m) is True
                states[s] = {k: np.asarray(t.data)
                             for k, t in m.get_states().items()}
            os.remove(mgr._digest_path(1))       # sidecar lost
            m2, _, _ = _compiled_mlp(seed=99)
            with pytest.warns(UserWarning, match="re-verified directly"):
                assert mgr.restore_latest(m2) == 2   # still verified!
            got = {k: np.asarray(t.data)
                   for k, t in m2.get_states().items()}
            for k in got:
                np.testing.assert_array_equal(got[k], states[1][k],
                                              err_msg=k)
            # but a marker digest that does NOT match the content is
            # rejected before touching live state — fallback to step 0
            # (the sidecar is still gone: the direct check is in force)
            marker = json.load(open(mgr._marker(1)))
            marker["digest"] = "crc32:deadbeef:10"
            json.dump(marker, open(mgr._marker(1), "w"))
            m3, _, _ = _compiled_mlp(seed=98)
            with pytest.warns(UserWarning, match="falling back"):
                assert mgr.restore_latest(m3) == 1
        finally:
            mgr.close()

    def test_corrupt_shard_restores_from_peer_same_step(self, tmp_path):
        """Digest-failed restore falls back ACROSS PEER SHARDS of the
        same step before dropping to an older one."""
        from singa_tpu.checkpoint import DistributedCheckpointManager
        from test_checkpoint import FakeCluster, _Hub
        hub = _Hub(2)
        ms, mgrs = [], []
        for r in range(2):
            m, tx, ty = _compiled_mlp()         # same seed: replicas
            ms.append((m, tx, ty))
            mgrs.append(DistributedCheckpointManager(
                tmp_path / "d", FakeCluster(r, hub)))
        try:
            for s in range(2):
                oks = [None, None]
                for m, tx, ty in ms:
                    m(tx, ty)

                def save(r, s=s):
                    oks[r] = mgrs[r].save(s, ms[r][0], force=True)

                ts = [threading.Thread(target=save, args=(r,))
                      for r in range(2)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(60)
                assert oks == [True, True]
            expected = {k: np.asarray(t.data)
                        for k, t in ms[0][0].get_states().items()}
            # rank0's OWN newest shard rots; rank1's copy is intact
            _tamper_digest(mgrs[0], 1)
            m2, _, _ = _compiled_mlp(seed=99)
            with pytest.warns(UserWarning, match="trying the next"):
                assert mgrs[0].restore_latest(m2) == 2   # SAME step!
            got = {k: np.asarray(t.data)
                   for k, t in m2.get_states().items()}
            for k in got:
                np.testing.assert_array_equal(got[k], expected[k],
                                              err_msg=k)
        finally:
            for g in mgrs:
                g.close()


# ---------------------------------------------------------------------------
# replica fingerprints
# ---------------------------------------------------------------------------

class TestReplicaFingerprints:
    def _replicated(self, perturb_device=None):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        devs = jax.devices()[:4]
        mesh = Mesh(np.array(devs), ("data",))
        sharding = NamedSharding(mesh, PartitionSpec())
        base = np.arange(8, dtype=np.float32)
        bufs = []
        for i, d in enumerate(devs):
            arr = base + 1e-3 if i == perturb_device else base
            bufs.append(jax.device_put(arr, d))
        return jax.make_array_from_single_device_arrays(
            base.shape, sharding, bufs), mesh

    def test_buffer_mismatch_names_the_divergent_device(self):
        import jax
        clean, _ = self._replicated()
        assert replica_buffer_mismatches({"w": clean}) == {}
        bad, _ = self._replicated(perturb_device=2)
        out = replica_buffer_mismatches({"w": bad})
        assert list(out) == ["w"]
        assert out["w"] == [str(jax.devices()[2])]
        # sharded (non-replicated) arrays are skipped, not flagged
        from jax.sharding import NamedSharding, PartitionSpec, Mesh
        mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
        sharded = jax.device_put(
            np.arange(8, dtype=np.float32),
            NamedSharding(mesh, PartitionSpec("data")))
        assert replica_buffer_mismatches({"s": sharded}) == {}

    def test_in_graph_fingerprint_all_gathers_and_detects(self):
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        from singa_tpu.parallel import communicator

        def check(arr, mesh):
            def body(x):
                return communicator.replica_fingerprint([x], "data")

            with communicator.collective_context("data"):
                # check_rep=False: the whole POINT is that "replicated"
                # inputs may hold divergent per-device buffers
                f = shard_map(body, mesh=mesh, in_specs=P(),
                              out_specs=(P(), P()), check_rep=False)
                gathered, agree = jax.jit(f)(arr)
            assert gathered.shape == (4, 2)
            return bool(agree)

        clean, mesh = self._replicated()
        assert check(clean, mesh) is True
        bad, mesh = self._replicated(perturb_device=1)
        assert check(bad, mesh) is False

    def test_state_fingerprint_is_bit_exact(self):
        a = {"w": np.arange(6, dtype=np.float32)}
        b = {"w": np.arange(6, dtype=np.float32)}
        assert state_fingerprint(a) == state_fingerprint(b)
        b["w"].view(np.int32)[3] ^= 1     # single-bit SDC
        assert state_fingerprint(a) != state_fingerprint(b)


class TestQuarantineAndRollback:
    def test_two_rank_divergence_quarantined_then_recovers(self):
        """An injected single-replica divergence is detected, the step
        quarantined on EVERY rank, state rolls back to the last
        cluster-agreed checkpoint, and — the fault being one-shot —
        training completes with both replicas bit-identical."""
        import tempfile
        from singa_tpu.resilience import ResilientTrainer

        with tempfile.TemporaryDirectory() as td:
            addr = _coordinator_addr()
            results = [None, None]
            finals = [None, None]

            def run_rank(r):
                m, tx, ty = _compiled_mlp()
                faults = FaultPlan()
                if r == 1:
                    faults.diverge_at(5, times=1)
                cluster = make_cluster(r, 2, addr, FAST, faults=faults)
                trainer = ResilientTrainer(
                    m, td, save_interval_steps=2, cluster=cluster,
                    faults=faults, fingerprint_every=3,
                    exit_on_preempt=False,
                    install_signal_handlers=False,
                    commit_timeout=20, start_barrier_timeout=20,
                    verbose=False)
                try:
                    results[r] = trainer.run([(tx, ty)] * 4,
                                             num_steps=10)
                    finals[r] = {k: np.asarray(t.data) for k, t in
                                 m.get_states().items()}
                finally:
                    trainer.close()
                    cluster.close()

            ts = [threading.Thread(target=run_rank, args=(r,))
                  for r in (0, 1)]
            with pytest.warns(UserWarning, match="quarantined"):
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(120)
            for r in (0, 1):
                s = results[r]
                assert s is not None, f"rank {r} never finished"
                assert s["quarantined_steps"] == 1
                assert s["divergence_rollbacks"] == 1
                assert s["diverged"] is False        # recovered
                assert s["steps_run"] > 10           # re-ran the rewind
                # 1-vs-1 majority vote cannot attribute blame: exactly
                # one rank is named, consistently on both ranks
                assert len(s["divergent"]) == 1
            assert results[0]["divergent"] == results[1]["divergent"]
            for k in finals[0]:                      # replicas re-agree
                np.testing.assert_array_equal(finals[0][k],
                                              finals[1][k], err_msg=k)

    def test_fingerprint_off_by_default_zero_checks(self):
        import tempfile
        from singa_tpu.resilience import ResilientTrainer
        m, tx, ty = _compiled_mlp()
        with tempfile.TemporaryDirectory() as td:
            trainer = ResilientTrainer(
                m, td, save_interval_steps=2,
                exit_on_preempt=False, install_signal_handlers=False,
                verbose=False)
            try:
                s = trainer.run([(tx, ty)] * 4, num_steps=4)
            finally:
                trainer.close()
            assert s["fingerprints"] == 0
            assert s["quarantined_steps"] == 0
