"""Unit tests for bench.py's window-salvage selection logic.

The round's only perf evidence can ride on these few functions (the TPU
tunnel opens rarely and drops mid-benchmark), so the partial-vs-complete
and banked-vs-live preferences are pinned here hermetically — no
hardware, no subprocesses.
"""

import json
import time

import bench


def _ts(age_s=0):
    return time.strftime("%Y-%m-%dT%H:%M:%S",
                         time.localtime(time.time() - age_s))


def _bench_rec(age_s=0, timing="slope-readback", **extra):
    rec = {"event": "bench", "ts": _ts(age_s), "platform": "tpu",
           "device_kind": "TPU v5 lite", "throughput": 1000.0,
           "step_ms": 32.0, "timing": timing}
    rec.update(extra)
    return rec


MAX_AGE = 14 * 3600


def test_last_result_line_picks_newest_and_stamps_marker():
    out = "\n".join([
        "garbage not json",
        json.dumps({"smoke": "device"}),
        json.dumps({"throughput": 1.0, "partial": "fp32"}),
        json.dumps({"throughput": 2.0, "partial": "bf16"}),
    ])
    res = bench._last_result_line(out, "partial_timeout", "killed")
    assert res["throughput"] == 2.0
    assert res["partial_timeout"] == "killed"
    assert bench._last_result_line("no json here") is None


def test_is_complete_and_n_legs():
    full = _bench_rec(bf16_throughput=2000.0, lm_tokens_per_sec=1e5)
    assert bench._is_complete(full) and bench._n_legs(full) == 3
    part = _bench_rec(partial_timeout="killed after 600s")
    assert not bench._is_complete(part) and bench._n_legs(part) == 1
    # progress-line marker counts as partial too
    assert not bench._is_complete(_bench_rec(partial="fp32"))


def test_live_complete_result_passes_through():
    live = _bench_rec(bf16_throughput=2000.0)
    res, is_live = bench._fold_banked(live, [], MAX_AGE, [])
    assert res is live and is_live


def test_banked_complete_reported_when_tunnel_down():
    banked = _bench_rec(age_s=3600)
    res, is_live = bench._fold_banked(None, [banked], MAX_AGE, [])
    assert not is_live
    assert res["measured_at"] == banked["ts"]
    assert res["throughput"] == 1000.0


def test_complete_banked_beats_newer_partial():
    complete = _bench_rec(age_s=7200, bf16_throughput=2000.0,
                          lm_tokens_per_sec=1e5)
    partial = _bench_rec(age_s=60, partial_timeout="killed after 600s")
    res, is_live = bench._fold_banked(None, [complete, partial],
                                      MAX_AGE, [])
    assert res["measured_at"] == complete["ts"]
    assert res["lm_tokens_per_sec"] == 1e5


def test_live_partial_loses_to_banked_complete():
    complete = _bench_rec(age_s=7200, bf16_throughput=2000.0)
    live_partial = _bench_rec(partial_crash="child rc=1")
    errors = []
    res, is_live = bench._fold_banked(
        live_partial, [complete, live_partial], MAX_AGE, errors)
    assert not is_live
    assert res["measured_at"] == complete["ts"]
    assert any("live run was partial" in e for e in errors)


def test_live_partial_kept_when_nothing_complete_banked():
    live_partial = _bench_rec(partial_timeout="killed after 1500s")
    res, is_live = bench._fold_banked(live_partial, [live_partial],
                                      MAX_AGE, [])
    assert is_live and res is live_partial


def test_honest_timing_preferred_over_suspect():
    suspect = _bench_rec(age_s=7200, timing="block_until_ready",
                         bf16_throughput=9999.0)
    honest_partial = _bench_rec(age_s=60,
                                partial_timeout="killed after 600s")
    res, _ = bench._fold_banked(None, [suspect, honest_partial],
                                MAX_AGE, [])
    assert res["timing"] == "slope-readback"
    assert "timing_suspect" not in res


def test_suspect_record_carried_with_marker_as_last_resort():
    suspect = _bench_rec(age_s=7200, timing="block_until_ready")
    res, _ = bench._fold_banked(None, [suspect], MAX_AGE, [])
    assert "timing_suspect" in res


def test_age_cap_excludes_stale_records():
    stale = _bench_rec(age_s=MAX_AGE + 3600)
    res, _ = bench._fold_banked(None, [stale], MAX_AGE, [])
    assert res is None


def test_tpu_phase_partial_does_not_cancel_retry(monkeypatch):
    """Attempt 1 salvages a partial; attempt 2 (warm compile cache) must
    still run — and its complete result wins."""
    monkeypatch.setattr(bench, "_probe_tpu", lambda t: ("ok", None))
    monkeypatch.setattr(bench, "_attempt_smoke", lambda t: [])
    monkeypatch.setattr(bench, "_record_obs", lambda *a, **k: None)
    partial = _bench_rec(partial_timeout="killed after 1500s")
    full = _bench_rec(bf16_throughput=2000.0, lm_tokens_per_sec=1e5)
    attempts = iter([(partial, None), (full, None)])
    monkeypatch.setattr(bench, "_attempt",
                        lambda p, t: next(attempts))
    errors = []
    res, _ = bench._tpu_phase(errors)
    assert res is full
    assert any("tpu#1" in e for e in errors)


def test_tpu_phase_keeps_best_partial_when_no_attempt_completes(
        monkeypatch):
    monkeypatch.setattr(bench, "_probe_tpu", lambda t: ("ok", None))
    monkeypatch.setattr(bench, "_attempt_smoke", lambda t: [])
    monkeypatch.setattr(bench, "_record_obs", lambda *a, **k: None)
    one_leg = _bench_rec(partial_timeout="killed after 1500s")
    two_leg = _bench_rec(bf16_throughput=2000.0,
                         partial_crash="child rc=1")
    attempts = iter([(two_leg, None), (one_leg, None)])
    monkeypatch.setattr(bench, "_attempt", lambda p, t: next(attempts))
    res, _ = bench._tpu_phase([])
    assert res is two_leg   # more legs wins over recency


def test_banked_partial_with_more_legs_beats_newer_live_partial():
    """Mirror of _tpu_phase's best-partial rule in the banked pool:
    a 2-leg partial banked earlier must not be shadowed by a newer
    1-leg live partial."""
    two_leg = _bench_rec(age_s=3600, bf16_throughput=2000.0,
                         partial_timeout="killed after 1500s")
    one_leg_live = _bench_rec(partial_crash="child rc=1")
    res, is_live = bench._fold_banked(
        one_leg_live, [two_leg, one_leg_live], MAX_AGE, [])
    assert not is_live
    assert res["bf16_throughput"] == 2000.0


def test_leg_guard_passes_through_and_times_out():
    """Thread watchdog: returns results, propagates leg exceptions, and
    a hung leg raises a TimeoutError NAMING the leg (the 04:34 lost
    window produced 25 minutes of silence instead)."""
    import time as _time
    assert bench._leg_guard(lambda: 42, 5, "ok") == 42
    try:
        bench._leg_guard(lambda: 1 / 0, 5, "boom")
        raise AssertionError("expected ZeroDivisionError")
    except ZeroDivisionError:
        pass
    try:
        bench._leg_guard(lambda: _time.sleep(30), 0.2, "fp32")
        raise AssertionError("expected TimeoutError")
    except TimeoutError as e:
        assert "fp32" in str(e)


def test_leg_timeout_record_counts_as_partial():
    rec = _bench_rec(bf16_error="bf16 leg hung > 900s",
                     leg_timeout="bf16")
    assert not bench._is_complete(rec)
    # and a complete banked record still beats it in the fold
    complete = _bench_rec(age_s=3600, bf16_throughput=2000.0)
    res, _ = bench._fold_banked(rec, [complete, rec], MAX_AGE, [])
    assert res["measured_at"] == complete["ts"]


def test_conv_layout_env_pin(monkeypatch, capsys):
    monkeypatch.setenv("BENCH_CONV_LAYOUT", "nhwc")
    assert bench._conv_layout() == ("NHWC", "env")
    monkeypatch.setenv("BENCH_CONV_LAYOUT", "NCHW")
    assert bench._conv_layout() == ("NCHW", "env")
    # a typo'd pin is diagnosed, not silently demoted to auto
    monkeypatch.setenv("BENCH_CONV_LAYOUT", "nwhc")
    monkeypatch.setattr(bench, "_load_obs", lambda: [])
    assert bench._conv_layout() == ("NCHW", "default-unmeasured")
    assert "not nchw|nhwc|auto" in capsys.readouterr().err


def test_extra_success_markers_single_source():
    """The watcher's retry table IS bench's marker table (round-4 review:
    two hand-maintained copies let a new leg's measurement silently miss
    the report)."""
    import importlib.util as iu
    import os
    spec = iu.spec_from_file_location(
        "tpu_watch", os.path.join(os.path.dirname(bench.__file__),
                                  "tools", "tpu_watch.py"))
    mod = iu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod._EXTRA_LEG_MARKERS is bench.EXTRA_SUCCESS_MARKERS
    assert set(mod.PRIORITY_LEGS) <= set(bench.EXTRA_SUCCESS_MARKERS)


def test_conv_layout_auto_uses_banked_ab(monkeypatch):
    """auto picks the measured winner of the newest banked layout A/B —
    the probe that runs BEFORE the full bench in a TPU window — and
    falls back to NCHW (labeled unmeasured) when none exists."""
    monkeypatch.delenv("BENCH_CONV_LAYOUT", raising=False)
    monkeypatch.setattr(bench, "_load_obs", lambda: [])
    assert bench._conv_layout() == ("NCHW", "default-unmeasured")
    obs = [
        {"event": "extra", "ts": _ts(7200),
         "extra": "resnet_layout_ab", "winner": "NCHW"},
        {"event": "extra", "ts": _ts(3600),
         "extra": "resnet_layout_ab", "winner": "NHWC"},
    ]
    monkeypatch.setattr(bench, "_load_obs", lambda: obs)
    assert bench._conv_layout() == ("NHWC", "measured-ab")
    # error-shaped records (no winner) are skipped
    obs.append({"event": "extra", "extra": "resnet_layout_ab_error",
                "error": "x"})
    assert bench._conv_layout() == ("NHWC", "measured-ab")


def test_fold_extras_latest_per_leg_and_compact_profile():
    obs = [
        {"event": "extra", "ts": _ts(7200),
         "extra": "lm_decode_tokens_per_sec", "value": 100.0},
        {"event": "extra", "ts": _ts(3600),
         "extra": "lm_decode_tokens_per_sec", "value": 120.0},
        {"event": "extra", "ts": _ts(3600),
         "extra": "lm_decode_tokens_per_sec_error", "error": "boom"},
        {"event": "extra", "ts": _ts(1800),
         "extra": "resnet50_bf16_fusion_profile",
         "total_measured_s": 0.5,
         "top": [{"op": f"f{i}", "pct": 10} for i in range(10)]},
        {"event": "smoke", "smoke": "device"},
    ]
    out = bench._fold_extras(obs)
    # newest success wins; error records never fold
    assert out["lm_decode_tokens_per_sec"]["value"] == 120.0
    assert "error" not in out["lm_decode_tokens_per_sec"]
    # profile folds compact: top-3 only
    assert len(out["resnet50_bf16_fusion_profile"]["top"]) == 3
    assert out["resnet50_bf16_fusion_profile"]["total_measured_s"] == 0.5


def test_peak_flops_per_dtype(monkeypatch):
    monkeypatch.delenv("BENCH_PEAK_TFLOPS", raising=False)
    monkeypatch.delenv("BENCH_PEAK_TFLOPS_FP32", raising=False)
    # no public fp32 peak: both dtypes get the chip (bf16) figure...
    assert bench._peak_flops("TPU v5 lite") == 197e12
    assert bench._peak_flops("TPU v5 lite", dtype="fp32") == 197e12
    # ...unless the caller supplies a distinct fp32 denominator
    monkeypatch.setenv("BENCH_PEAK_TFLOPS_FP32", "50")
    assert bench._peak_flops("TPU v5 lite", dtype="fp32") == 50e12
    assert bench._peak_flops("TPU v5 lite") == 197e12


def test_resnet_stem_env_and_banked(monkeypatch, capsys):
    monkeypatch.setenv("BENCH_RESNET_STEM", "space_to_depth")
    assert bench._resnet_stem() == ("space_to_depth", "env")
    monkeypatch.setenv("BENCH_RESNET_STEM", "s2d")   # typo: warn, auto
    monkeypatch.setattr(bench, "_load_obs", lambda: [])
    assert bench._resnet_stem() == ("conv7", "default-unmeasured")
    assert "conv7|space_to_depth|auto" in capsys.readouterr().err
    monkeypatch.delenv("BENCH_RESNET_STEM")
    monkeypatch.setattr(bench, "_load_obs", lambda: [
        {"event": "extra", "ts": _ts(60), "extra": "resnet_stem_ab",
         "winner": "space_to_depth"}])
    assert bench._resnet_stem() == ("space_to_depth", "measured-ab")


def test_serving_sweep_banks_configs_across_leg_timeout():
    """The sweep's per-config banking rides a caller-shared box, so a
    _leg_guard timeout salvages every config that finished instead of
    discarding the whole leg (the call site stamps the salvage
    ``partial: True``)."""
    import inspect
    import time as _time
    assert "out" in inspect.signature(
        bench._measure_serving_sweep).parameters

    def fake_sweep(out):
        out["configs"] = []
        out["configs"].append({"kv_layout": "ring", "slots": 2})
        out["configs"].append({"kv_layout": "paged", "slots": 4})
        _time.sleep(30)                 # the third config hangs

    box = {}
    try:
        bench._leg_guard(lambda: fake_sweep(box), 0.3, "serving_sweep")
        raise AssertionError("expected TimeoutError")
    except TimeoutError:
        pass
    assert len(box["configs"]) == 2     # both finished configs survive
    salvaged = dict(box, partial=True)
    assert salvaged["partial"] and len(salvaged["configs"]) == 2
