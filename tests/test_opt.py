"""Optimizer update math vs hand-computed numpy (reference
test/python/test_opt.py) + scheduler + state roundtrips."""

import numpy as np
import jax.numpy as jnp

from singa_tpu import opt
from singa_tpu.tensor import Tensor


def mkparam(val):
    p = Tensor(data=np.asarray(val, np.float32), requires_grad=True,
               stores_grad=True)
    p.name = "w"
    return p


def mkgrad(val):
    return Tensor(data=np.asarray(val, np.float32), requires_grad=False)


class TestSGD:
    def test_vanilla(self):
        p = mkparam([1.0, 2.0])
        sgd = opt.SGD(lr=0.1)
        sgd.apply("w", p, mkgrad([0.5, -0.5]))
        np.testing.assert_allclose(np.asarray(p.data), [0.95, 2.05])

    def test_weight_decay(self):
        p = mkparam([1.0])
        sgd = opt.SGD(lr=0.1, weight_decay=0.1)
        sgd.apply("w", p, mkgrad([0.0]))
        np.testing.assert_allclose(np.asarray(p.data), [1.0 - 0.1 * 0.1])

    def test_momentum(self):
        p = mkparam([0.0])
        sgd = opt.SGD(lr=1.0, momentum=0.9)
        g = mkgrad([1.0])
        sgd.apply("w", p, g)           # buf=1, p=-1
        sgd.apply("w", p, g)           # buf=1.9, p=-2.9
        np.testing.assert_allclose(np.asarray(p.data), [-2.9], rtol=1e-6)

    def test_nesterov(self):
        p = mkparam([0.0])
        sgd = opt.SGD(lr=1.0, momentum=0.5, nesterov=True)
        sgd.apply("w", p, mkgrad([1.0]))
        # buf=1; update = g + m*buf = 1.5
        np.testing.assert_allclose(np.asarray(p.data), [-1.5])


class TestRMSProp:
    def test_update(self):
        p = mkparam([1.0])
        o = opt.RMSProp(lr=0.1, rho=0.9, epsilon=1e-8)
        o.apply("w", p, mkgrad([2.0]))
        rms = 0.1 * 4.0
        expect = 1.0 - 0.1 * 2.0 / np.sqrt(rms + 1e-8)
        np.testing.assert_allclose(np.asarray(p.data), [expect], rtol=1e-6)


class TestAdaGrad:
    def test_update(self):
        p = mkparam([1.0])
        o = opt.AdaGrad(lr=0.1, epsilon=1e-8)
        o.apply("w", p, mkgrad([2.0]))
        expect = 1.0 - 0.1 * 2.0 / np.sqrt(4.0 + 1e-8)
        np.testing.assert_allclose(np.asarray(p.data), [expect], rtol=1e-6)


class TestAdam:
    def test_update(self):
        p = mkparam([1.0])
        o = opt.Adam(lr=0.01, beta_1=0.9, beta_2=0.999, epsilon=1e-8)
        g = 2.0
        o.apply("w", p, mkgrad([g]))
        m = 0.1 * g
        v = 0.001 * g * g
        mhat = m / (1 - 0.9)
        vhat = v / (1 - 0.999)
        expect = 1.0 - 0.01 * mhat / (np.sqrt(vhat) + 1e-8)
        np.testing.assert_allclose(np.asarray(p.data), [expect], rtol=1e-5)

    def test_amsgrad_monotone_vmax(self):
        p = mkparam([1.0])
        o = opt.Adam(lr=0.01, amsgrad=True)
        o.apply("w", p, mkgrad([5.0]))
        o.step()
        vmax1 = float(o._aux["w:vmax"].data[0])
        o.apply("w", p, mkgrad([0.1]))
        vmax2 = float(o._aux["w:vmax"].data[0])
        assert vmax2 >= vmax1


class TestRegularizerConstraint:
    """Reference include/singa/model/optimizer.h:151-244 +
    src/model/optimizer/optimizer.cc:63-99."""

    def test_l2_regularizer(self):
        p = mkparam([2.0])
        sgd = opt.SGD(lr=0.1)
        sgd.regularizer = opt.Regularizer("l2", coefficient=0.5)
        sgd.apply("w", p, mkgrad([1.0]))
        # grad = 1 + 0.5*2 = 2 ; p = 2 - 0.1*2
        np.testing.assert_allclose(np.asarray(p.data), [1.8], rtol=1e-6)

    def test_l1_regularizer(self):
        p = mkparam([-3.0])
        sgd = opt.SGD(lr=0.1)
        sgd.regularizer = opt.Regularizer("l1", coefficient=0.5)
        sgd.apply("w", p, mkgrad([1.0]))
        # grad = 1 + 0.5*sign(-3) = 0.5 ; p = -3 - 0.05
        np.testing.assert_allclose(np.asarray(p.data), [-3.05], rtol=1e-6)

    def test_l2_norm_constraint_clips(self):
        p = mkparam([0.0, 0.0])
        sgd = opt.SGD(lr=1.0)
        sgd.constraint = opt.Constraint("l2", threshold=1.0)
        sgd.apply("w", p, mkgrad([3.0, 4.0]))   # norm 5 -> scaled to 1
        np.testing.assert_allclose(np.asarray(p.data), [-0.6, -0.8],
                                   rtol=1e-6)

    def test_l2_norm_constraint_noop_below_threshold(self):
        p = mkparam([0.0])
        sgd = opt.SGD(lr=1.0)
        sgd.constraint = opt.Constraint("l2", threshold=10.0)
        sgd.apply("w", p, mkgrad([0.5]))
        np.testing.assert_allclose(np.asarray(p.data), [-0.5], rtol=1e-6)

    def test_value_constraint(self):
        p = mkparam([0.0, 0.0])
        sgd = opt.SGD(lr=1.0)
        sgd.constraint = opt.Constraint("value", threshold=0.25)
        sgd.apply("w", p, mkgrad([3.0, -4.0]))
        np.testing.assert_allclose(np.asarray(p.data), [-0.25, 0.25])

    def test_per_param_registration_wins(self):
        sgd = opt.SGD(lr=1.0)
        sgd.regularizer = opt.Regularizer("l2", coefficient=100.0)
        sgd.register("w", regularizer=opt.Regularizer("notset"),
                     lr_multiplier=0.5)
        p = mkparam([1.0])
        sgd.apply("w", p, mkgrad([1.0]))
        # per-param notset regularizer overrides global; lr scaled by 0.5
        np.testing.assert_allclose(np.asarray(p.data), [0.5], rtol=1e-6)
        # unregistered param takes the global regularizer
        q = mkparam([1.0])
        q.name = "v"
        sgd.apply("v", q, mkgrad([0.0]))
        np.testing.assert_allclose(np.asarray(q.data), [-99.0], rtol=1e-5)

    def test_constraint_in_compiled_step(self):
        """Clipping must survive jit (traced, no python branching)."""
        from singa_tpu import device, layer, model

        class Net(model.Model):
            def __init__(self):
                super().__init__()
                self.fc = layer.Linear(2)
                self.loss_fn = layer.MeanSquareError()

            def forward(self, x):
                return self.fc(x)

            def train_one_batch(self, x, y):
                o = self.forward(x)
                ls = self.loss_fn(o, y)
                self.optimizer(ls)
                return o, ls

        dev = device.create_cpu_device()
        m = Net()
        sgd = opt.SGD(lr=0.1)
        sgd.constraint = opt.Constraint("l2", threshold=1e-3)
        m.set_optimizer(sgd)
        x = Tensor(data=np.random.randn(4, 3).astype(np.float32),
                   device=dev, requires_grad=False)
        y = Tensor(data=np.random.randn(4, 2).astype(np.float32) * 100,
                   device=dev, requires_grad=False)
        m.compile([x], is_train=True, use_graph=True)
        w0 = {k: np.asarray(v.data).copy()
              for k, v in m.get_states().items()}
        m(x, y)
        m(x, y)  # compiled step
        for k, v in m.get_states().items():
            delta = np.linalg.norm(np.asarray(v.data) - w0[k])
            # 2 steps, each grad clipped to 1e-3, lr 0.1
            assert delta <= 2 * 0.1 * 1e-3 * 1.01, (k, delta)


class TestSchedulers:
    def test_constant(self):
        s = opt.Constant(0.25)
        assert float(s(jnp.asarray(10.0))) == 0.25

    def test_exponential(self):
        s = opt.ExponentialDecay(1.0, decay_steps=10, decay_rate=0.5)
        np.testing.assert_allclose(float(s(jnp.asarray(10.0))), 0.5)
        np.testing.assert_allclose(float(s(jnp.asarray(5.0))),
                                   0.5 ** 0.5, rtol=1e-6)

    def test_exponential_staircase(self):
        s = opt.ExponentialDecay(1.0, 10, 0.5, staircase=True)
        np.testing.assert_allclose(float(s(jnp.asarray(9.0))), 1.0)
        np.testing.assert_allclose(float(s(jnp.asarray(19.0))), 0.5)

    def test_optimizer_uses_schedule(self):
        o = opt.SGD(lr=opt.ExponentialDecay(1.0, 1, 0.5, staircase=True))
        p = mkparam([0.0])
        o.apply("w", p, mkgrad([1.0]))   # lr=1 at step 0
        o.step()
        o.apply("w", p, mkgrad([1.0]))   # lr=0.5 at step 1
        np.testing.assert_allclose(np.asarray(p.data), [-1.5])


class TestStates:
    def test_roundtrip(self):
        o = opt.Adam(lr=0.01)
        p = mkparam([1.0, 2.0])
        o.apply("w", p, mkgrad([0.1, 0.2]))
        o.step()
        states = o.get_states()
        o2 = opt.Adam(lr=0.01)
        o2.set_states(states)
        assert float(o2.step_counter.data) == 1.0
        np.testing.assert_allclose(np.asarray(o2._aux["w:m"].data),
                                   np.asarray(o._aux["w:m"].data))

    def test_dist_states_roundtrip(self):
        d = opt.DistOpt(opt.SGD(lr=0.1), world_size=1)
        p = mkparam([1.0])
        d.opt.apply("w", p, mkgrad([1.0]))
        d.step()
        s = d.get_states()
        d2 = opt.DistOpt(opt.SGD(lr=0.1), world_size=1)
        d2.set_states(s)
        assert float(d2.step_counter.data) == 1.0
