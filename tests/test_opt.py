"""Optimizer update math vs hand-computed numpy (reference
test/python/test_opt.py) + scheduler + state roundtrips."""

import numpy as np
import jax.numpy as jnp

from singa_tpu import opt
from singa_tpu.tensor import Tensor


def mkparam(val):
    p = Tensor(data=np.asarray(val, np.float32), requires_grad=True,
               stores_grad=True)
    p.name = "w"
    return p


def mkgrad(val):
    return Tensor(data=np.asarray(val, np.float32), requires_grad=False)


class TestSGD:
    def test_vanilla(self):
        p = mkparam([1.0, 2.0])
        sgd = opt.SGD(lr=0.1)
        sgd.apply("w", p, mkgrad([0.5, -0.5]))
        np.testing.assert_allclose(np.asarray(p.data), [0.95, 2.05])

    def test_weight_decay(self):
        p = mkparam([1.0])
        sgd = opt.SGD(lr=0.1, weight_decay=0.1)
        sgd.apply("w", p, mkgrad([0.0]))
        np.testing.assert_allclose(np.asarray(p.data), [1.0 - 0.1 * 0.1])

    def test_momentum(self):
        p = mkparam([0.0])
        sgd = opt.SGD(lr=1.0, momentum=0.9)
        g = mkgrad([1.0])
        sgd.apply("w", p, g)           # buf=1, p=-1
        sgd.apply("w", p, g)           # buf=1.9, p=-2.9
        np.testing.assert_allclose(np.asarray(p.data), [-2.9], rtol=1e-6)

    def test_nesterov(self):
        p = mkparam([0.0])
        sgd = opt.SGD(lr=1.0, momentum=0.5, nesterov=True)
        sgd.apply("w", p, mkgrad([1.0]))
        # buf=1; update = g + m*buf = 1.5
        np.testing.assert_allclose(np.asarray(p.data), [-1.5])


class TestRMSProp:
    def test_update(self):
        p = mkparam([1.0])
        o = opt.RMSProp(lr=0.1, rho=0.9, epsilon=1e-8)
        o.apply("w", p, mkgrad([2.0]))
        rms = 0.1 * 4.0
        expect = 1.0 - 0.1 * 2.0 / np.sqrt(rms + 1e-8)
        np.testing.assert_allclose(np.asarray(p.data), [expect], rtol=1e-6)


class TestAdaGrad:
    def test_update(self):
        p = mkparam([1.0])
        o = opt.AdaGrad(lr=0.1, epsilon=1e-8)
        o.apply("w", p, mkgrad([2.0]))
        expect = 1.0 - 0.1 * 2.0 / np.sqrt(4.0 + 1e-8)
        np.testing.assert_allclose(np.asarray(p.data), [expect], rtol=1e-6)


class TestAdam:
    def test_update(self):
        p = mkparam([1.0])
        o = opt.Adam(lr=0.01, beta_1=0.9, beta_2=0.999, epsilon=1e-8)
        g = 2.0
        o.apply("w", p, mkgrad([g]))
        m = 0.1 * g
        v = 0.001 * g * g
        mhat = m / (1 - 0.9)
        vhat = v / (1 - 0.999)
        expect = 1.0 - 0.01 * mhat / (np.sqrt(vhat) + 1e-8)
        np.testing.assert_allclose(np.asarray(p.data), [expect], rtol=1e-5)

    def test_amsgrad_monotone_vmax(self):
        p = mkparam([1.0])
        o = opt.Adam(lr=0.01, amsgrad=True)
        o.apply("w", p, mkgrad([5.0]))
        o.step()
        vmax1 = float(o._aux["w:vmax"].data[0])
        o.apply("w", p, mkgrad([0.1]))
        vmax2 = float(o._aux["w:vmax"].data[0])
        assert vmax2 >= vmax1


class TestSchedulers:
    def test_constant(self):
        s = opt.Constant(0.25)
        assert float(s(jnp.asarray(10.0))) == 0.25

    def test_exponential(self):
        s = opt.ExponentialDecay(1.0, decay_steps=10, decay_rate=0.5)
        np.testing.assert_allclose(float(s(jnp.asarray(10.0))), 0.5)
        np.testing.assert_allclose(float(s(jnp.asarray(5.0))),
                                   0.5 ** 0.5, rtol=1e-6)

    def test_exponential_staircase(self):
        s = opt.ExponentialDecay(1.0, 10, 0.5, staircase=True)
        np.testing.assert_allclose(float(s(jnp.asarray(9.0))), 1.0)
        np.testing.assert_allclose(float(s(jnp.asarray(19.0))), 0.5)

    def test_optimizer_uses_schedule(self):
        o = opt.SGD(lr=opt.ExponentialDecay(1.0, 1, 0.5, staircase=True))
        p = mkparam([0.0])
        o.apply("w", p, mkgrad([1.0]))   # lr=1 at step 0
        o.step()
        o.apply("w", p, mkgrad([1.0]))   # lr=0.5 at step 1
        np.testing.assert_allclose(np.asarray(p.data), [-1.5])


class TestStates:
    def test_roundtrip(self):
        o = opt.Adam(lr=0.01)
        p = mkparam([1.0, 2.0])
        o.apply("w", p, mkgrad([0.1, 0.2]))
        o.step()
        states = o.get_states()
        o2 = opt.Adam(lr=0.01)
        o2.set_states(states)
        assert float(o2.step_counter.data) == 1.0
        np.testing.assert_allclose(np.asarray(o2._aux["w:m"].data),
                                   np.asarray(o._aux["w:m"].data))

    def test_dist_states_roundtrip(self):
        d = opt.DistOpt(opt.SGD(lr=0.1), world_size=1)
        p = mkparam([1.0])
        d.opt.apply("w", p, mkgrad([1.0]))
        d.step()
        s = d.get_states()
        d2 = opt.DistOpt(opt.SGD(lr=0.1), world_size=1)
        d2.set_states(s)
        assert float(d2.step_counter.data) == 1.0
