"""Fused-kernel numerics + accounting (``pallas`` tier: the exact TPU
kernel math runs under ``pl.pallas_call(interpret=True)`` on CPU).

Covers the MFU-push kernels of ops/fused_optim.py / ops/fused_epilogue.py:

- SGD-momentum / Adam one-HBM-pass updates: parity against the
  reference ``opt.SGD``/``opt.Adam`` math (bitwise for f32 SGD),
  including padding tails, weight decay, nesterov, and lr schedules;
- eligibility gating: regularizer/constraint params decline per-param,
  ``force_reference`` declines everything, off switch is the default;
- end-to-end: a model trained with ``fused=True`` matches its
  reference twin state-for-state, with ``n_traces`` still 1;
- FLOPs accounting (the satellite fix): ``Model.step_flops`` of the
  fused program equals the unfused program's EXACTLY — no phantom MFU
  jump from cost analysis losing (or inflating) the custom call;
- the conv epilogue: scale/shift+ReLU kernel parity in both layouts,
  the BN→ReLU peephole under a jit matching the reference eval, and
  the enable gate defaulting off.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from singa_tpu import tensor, device, opt, layer, model
from singa_tpu.ops import fused_epilogue, fused_optim

pytestmark = pytest.mark.pallas


@pytest.fixture(autouse=True)
def _interpret_kernels():
    prev = fused_optim.FORCE_PALLAS_INTERPRET
    fused_optim.FORCE_PALLAS_INTERPRET = True
    try:
        yield
    finally:
        fused_optim.FORCE_PALLAS_INTERPRET = prev


def _rand(shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


# ---------------------------------------------------------------------------
# kernel-level parity
# ---------------------------------------------------------------------------

class TestSgdKernel:
    # shapes straddle the (rows, 128) tiling: exact tiles, ragged
    # tails, sub-lane scalars-ish vectors, >1 grid block
    SHAPES = [(1024,), (64, 64), (7,), (13, 10), (4099,), (3, 3, 3, 5)]

    @pytest.mark.parametrize("wd,nesterov", [(0.0, False), (1e-4, False),
                                             (1e-4, True)])
    def test_matches_reference_math(self, wd, nesterov):
        for i, shape in enumerate(self.SHAPES):
            p, g, m = _rand(shape, i), _rand(shape, i + 50), \
                _rand(shape, i + 100)
            pn, mn = fused_optim.sgd_momentum_update(
                jnp.asarray(p), jnp.asarray(g), jnp.asarray(m),
                jnp.float32(0.1), momentum=0.9, dampening=0.0,
                weight_decay=wd, nesterov=nesterov)
            g2 = g + wd * p
            m_ref = (0.9 * m + g2).astype(np.float32)
            upd = g2 + 0.9 * m_ref if nesterov else m_ref
            p_ref = (p - 0.1 * upd).astype(np.float32)
            assert pn.shape == shape and mn.shape == shape
            np.testing.assert_allclose(np.asarray(pn), p_ref, atol=1e-6)
            np.testing.assert_allclose(np.asarray(mn), m_ref, atol=1e-6)

    def test_pad_tail_does_not_leak(self):
        # a shape whose pad region, if mishandled, would fold garbage
        # into real lanes: exact equality with an unpadded same-values
        # run via a round-trip through a larger exact-tile shape
        p, g, m = _rand((1025,)), _rand((1025,), 1), _rand((1025,), 2)
        pn, mn = fused_optim.sgd_momentum_update(
            jnp.asarray(p), jnp.asarray(g), jnp.asarray(m),
            jnp.float32(0.5), momentum=0.5)
        m_ref = 0.5 * m + g
        np.testing.assert_allclose(np.asarray(mn), m_ref, atol=1e-6)
        np.testing.assert_allclose(np.asarray(pn), p - 0.5 * m_ref,
                                   atol=1e-6)


class TestAdamKernel:
    def test_matches_reference_math(self):
        for shape in ((513,), (32, 32), (9, 7)):
            p, g = _rand(shape), _rand(shape, 1)
            m, v = _rand(shape, 2), np.abs(_rand(shape, 3))
            t = 4.0
            bc1, bc2 = 1 - 0.9 ** t, 1 - 0.999 ** t
            pn, mn, vn = fused_optim.adam_update(
                jnp.asarray(p), jnp.asarray(g), jnp.asarray(m),
                jnp.asarray(v), jnp.float32(0.01), jnp.float32(bc1),
                jnp.float32(bc2), beta_1=0.9, beta_2=0.999,
                epsilon=1e-8, weight_decay=1e-4)
            g2 = g + 1e-4 * p
            m_ref = 0.9 * m + 0.1 * g2
            v_ref = 0.999 * v + 0.001 * g2 * g2
            p_ref = p - 0.01 * (m_ref / bc1) / (np.sqrt(v_ref / bc2)
                                                + 1e-8)
            np.testing.assert_allclose(np.asarray(pn), p_ref, atol=1e-5)
            np.testing.assert_allclose(np.asarray(mn), m_ref, atol=1e-6)
            np.testing.assert_allclose(np.asarray(vn), v_ref, atol=1e-6)


class TestRmspropKernel:
    def test_matches_reference_math(self):
        for shape in ((1024,), (13, 10), (4099,)):
            p, g = _rand(shape), _rand(shape, 1)
            r = np.abs(_rand(shape, 2))
            pn, rn = fused_optim.rmsprop_update(
                jnp.asarray(p), jnp.asarray(g), jnp.asarray(r),
                jnp.float32(0.05), rho=0.9, epsilon=1e-8,
                weight_decay=1e-4)
            g2 = g + 1e-4 * p
            r_ref = 0.9 * r + 0.1 * g2 * g2
            p_ref = p - 0.05 * g2 / np.sqrt(r_ref + 1e-8)
            assert pn.shape == shape and rn.shape == shape
            np.testing.assert_allclose(np.asarray(rn), r_ref, atol=1e-6)
            np.testing.assert_allclose(np.asarray(pn), p_ref, atol=1e-6)


class TestAdagradKernel:
    def test_matches_reference_math(self):
        for shape in ((513,), (32, 32), (9, 7)):
            p, g = _rand(shape), _rand(shape, 1)
            h = np.abs(_rand(shape, 2))
            pn, hn = fused_optim.adagrad_update(
                jnp.asarray(p), jnp.asarray(g), jnp.asarray(h),
                jnp.float32(0.1), epsilon=1e-8, weight_decay=0.0)
            h_ref = h + g * g
            p_ref = p - 0.1 * g / np.sqrt(h_ref + 1e-8)
            np.testing.assert_allclose(np.asarray(hn), h_ref, atol=1e-6)
            np.testing.assert_allclose(np.asarray(pn), p_ref, atol=1e-6)


# ---------------------------------------------------------------------------
# optimizer integration + gating
# ---------------------------------------------------------------------------

class _MLP(model.Model):
    def __init__(self, classes=3):
        super().__init__()
        self.fc1 = layer.Linear(32)
        self.relu = layer.ReLU()
        self.fc2 = layer.Linear(classes)
        self.loss_fn = layer.SoftMaxCrossEntropy()

    def forward(self, x):
        return self.fc2(self.relu(self.fc1(x)))

    def train_one_batch(self, x, y):
        out = self.forward(x)
        loss = self.loss_fn(out, y)
        self.optimizer(loss)
        return out, loss


def _train(optimizer, steps=5, seed=0):
    dev = device.create_cpu_device()
    dev.SetRandSeed(11)
    rng = np.random.RandomState(seed)
    m = _MLP()
    m.set_optimizer(optimizer)
    xs = rng.randn(16, 6).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)]
    tx = tensor.Tensor(data=xs, device=dev, requires_grad=False)
    ty = tensor.Tensor(data=ys, device=dev, requires_grad=False)
    m.compile([tx], is_train=True, use_graph=True)
    for _ in range(steps):
        m(tx, ty)
    states = {k: np.asarray(v.data) for k, v in m.get_states().items()}
    return states, m


class TestFusedOptimizers:
    def test_sgd_end_to_end_parity_bitwise(self):
        ref, _ = _train(opt.SGD(lr=0.1, momentum=0.9, weight_decay=1e-4))
        fus, mf = _train(opt.SGD(lr=0.1, momentum=0.9,
                                 weight_decay=1e-4, fused=True))
        rec = next(iter(mf._steps.values()))
        assert rec.get("fused_kinds") == ["sgd"], rec.get("fused_kinds")
        for k in ref:
            assert np.array_equal(ref[k], fus[k]), k

    def test_adam_end_to_end_parity(self):
        ref, _ = _train(opt.Adam(lr=0.01))
        fus, mf = _train(opt.Adam(lr=0.01, fused=True))
        rec = next(iter(mf._steps.values()))
        assert rec.get("fused_kinds") == ["adam"]
        for k in ref:
            np.testing.assert_allclose(ref[k], fus[k], atol=1e-6,
                                       err_msg=k)

    def test_rmsprop_end_to_end_parity_bitwise(self):
        ref, _ = _train(opt.RMSProp(lr=0.05, rho=0.9,
                                    weight_decay=1e-4))
        fus, mf = _train(opt.RMSProp(lr=0.05, rho=0.9,
                                     weight_decay=1e-4, fused=True))
        rec = next(iter(mf._steps.values()))
        assert rec.get("fused_kinds") == ["rmsprop"], \
            rec.get("fused_kinds")
        for k in ref:
            assert np.array_equal(ref[k], fus[k]), k

    def test_adagrad_end_to_end_parity_bitwise(self):
        ref, _ = _train(opt.AdaGrad(lr=0.1))
        fus, mf = _train(opt.AdaGrad(lr=0.1, fused=True))
        rec = next(iter(mf._steps.values()))
        assert rec.get("fused_kinds") == ["adagrad"]
        for k in ref:
            assert np.array_equal(ref[k], fus[k]), k

    def test_rmsprop_regularized_param_declines_per_param(self):
        o = opt.RMSProp(lr=0.05, fused=True)
        o.register("fc1.W", regularizer=opt.Regularizer("l2", 1e-3))
        o_ref = opt.RMSProp(lr=0.05)
        o_ref.register("fc1.W", regularizer=opt.Regularizer("l2", 1e-3))
        fus, mf = _train(o)
        ref, _ = _train(o_ref)
        for k in ref:
            assert np.array_equal(ref[k], fus[k]), k
        rec = next(iter(mf._steps.values()))
        assert rec.get("fused_kinds") == ["rmsprop"]

    def test_rmsprop_adagrad_flops_twin(self):
        _, mr = _train(opt.RMSProp(lr=0.05))
        _, mf = _train(opt.RMSProp(lr=0.05, fused=True))
        assert mr.step_flops(compute=True) == \
            mf.step_flops(compute=True)
        _, ar = _train(opt.AdaGrad(lr=0.1))
        _, af = _train(opt.AdaGrad(lr=0.1, fused=True))
        assert ar.step_flops(compute=True) == \
            af.step_flops(compute=True)

    def test_fused_keeps_n_traces_at_one(self):
        _, mf = _train(opt.SGD(lr=0.1, momentum=0.9, fused=True),
                       steps=6)
        rec = next(iter(mf._steps.values()))
        assert rec["n_traces"] == 1, rec["n_traces"]

    def test_lr_schedule_rides_the_kernel(self):
        sched = opt.ExponentialDecay(0.2, decay_steps=2, decay_rate=0.5)
        ref, _ = _train(opt.SGD(lr=sched, momentum=0.9), steps=6)
        sched2 = opt.ExponentialDecay(0.2, decay_steps=2, decay_rate=0.5)
        fus, _ = _train(opt.SGD(lr=sched2, momentum=0.9, fused=True),
                        steps=6)
        for k in ref:
            assert np.array_equal(ref[k], fus[k]), k

    def test_regularized_param_declines_per_param(self):
        o = opt.SGD(lr=0.1, momentum=0.9, fused=True)
        o.register("fc1.W", regularizer=opt.Regularizer("l2", 1e-3))
        o_ref = opt.SGD(lr=0.1, momentum=0.9)
        o_ref.register("fc1.W", regularizer=opt.Regularizer("l2", 1e-3))
        fus, mf = _train(o)
        ref, _ = _train(o_ref)
        for k in ref:
            assert np.array_equal(ref[k], fus[k]), k
        # the unregularized params still took the kernel
        rec = next(iter(mf._steps.values()))
        assert rec.get("fused_kinds") == ["sgd"]

    def test_force_reference_declines_everything(self):
        with fused_optim.force_reference():
            _, mf = _train(opt.SGD(lr=0.1, momentum=0.9, fused=True))
        rec = next(iter(mf._steps.values()))
        assert "fused_kinds" not in rec

    def test_default_is_reference(self):
        _, mf = _train(opt.SGD(lr=0.1, momentum=0.9))
        rec = next(iter(mf._steps.values()))
        assert "fused_kinds" not in rec

    def test_amsgrad_declines(self):
        _, mf = _train(opt.Adam(lr=0.01, amsgrad=True, fused=True))
        rec = next(iter(mf._steps.values()))
        assert "fused_kinds" not in rec


class TestFusedFlopsAccounting:
    """The satellite fix: cost analysis cannot see into a Pallas custom
    call, so a fused step's XLA-counted FLOPs would differ from the
    reference program's and MFU would move for free. step_flops must
    report IDENTICAL numbers for both."""

    def test_fused_equals_unfused_exactly(self):
        _, mr = _train(opt.SGD(lr=0.1, momentum=0.9, weight_decay=1e-4))
        _, mf = _train(opt.SGD(lr=0.1, momentum=0.9, weight_decay=1e-4,
                               fused=True))
        f_ref = mr.step_flops(compute=True)
        f_fus = mf.step_flops(compute=True)
        assert f_ref is not None and f_ref == f_fus, (f_ref, f_fus)

    def test_adam_fused_equals_unfused(self):
        _, mr = _train(opt.Adam(lr=0.01))
        _, mf = _train(opt.Adam(lr=0.01, fused=True))
        assert mr.step_flops(compute=True) == \
            mf.step_flops(compute=True)

    def test_cheap_path_stays_cheap(self):
        # compute=False on a fused program must not pay the twin
        # re-lower — it returns None until somebody computes
        _, mf = _train(opt.SGD(lr=0.1, momentum=0.9, fused=True))
        rec = next(iter(mf._steps.values()))
        assert "step_flops" not in rec
        assert mf.step_flops(compute=False) is None
        assert rec["n_traces"] == 1          # no hidden twin trace

    def test_twin_does_not_poison_live_state(self):
        _, mf = _train(opt.SGD(lr=0.1, momentum=0.9, fused=True))
        mf.step_flops(compute=True)
        assert not any(isinstance(t.data, jax.core.Tracer)
                       for t in mf._state_list)
        # and training continues
        dev = mf.dev
        rng = np.random.RandomState(4)
        tx = tensor.Tensor(data=rng.randn(16, 6).astype(np.float32),
                           device=dev, requires_grad=False)
        ty = tensor.Tensor(
            data=np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)],
            device=dev, requires_grad=False)
        out, loss = mf(tx, ty)
        assert np.isfinite(float(loss.data))


# ---------------------------------------------------------------------------
# conv epilogue
# ---------------------------------------------------------------------------

class TestFusedEpilogue:
    @pytest.mark.parametrize("layout,shape", [("NCHW", (2, 5, 7, 7)),
                                              ("NHWC", (2, 7, 7, 5)),
                                              ("NCHW", (1, 3, 16, 16))])
    def test_scale_shift_relu_parity(self, layout, shape):
        rng = np.random.RandomState(0)
        x = rng.randn(*shape).astype(np.float32)
        C = shape[1] if layout == "NCHW" else shape[-1]
        sc = (rng.rand(C) + 0.5).astype(np.float32)
        sh = rng.randn(C).astype(np.float32)
        got = fused_epilogue.scale_shift_relu(jnp.asarray(x), sc, sh,
                                              layout=layout)
        b = (1, C, 1, 1) if layout == "NCHW" else (1, 1, 1, C)
        ref = np.maximum(x * sc.reshape(b) + sh.reshape(b), 0)
        assert got.dtype == x.dtype and got.shape == x.shape
        np.testing.assert_allclose(np.asarray(got), ref, atol=1e-6)

    def test_vmem_budget_falls_back_to_reference(self):
        """A shape whose minimum legal block would exceed the VMEM
        budget (huge per-channel planes) must compute via plain XLA
        ops — same numbers, no Mosaic-doomed pallas_call."""
        assert fused_epilogue._block_rows(8, 262144) is None
        rng = np.random.RandomState(1)
        x = rng.randn(1, 2, 512, 512).astype(np.float32)
        sc = (rng.rand(2) + 0.5).astype(np.float32)
        sh = rng.randn(2).astype(np.float32)
        got = fused_epilogue.scale_shift_relu(jnp.asarray(x), sc, sh,
                                              layout="NCHW")
        ref = np.maximum(x * sc.reshape(1, 2, 1, 1)
                         + sh.reshape(1, 2, 1, 1), 0)
        np.testing.assert_allclose(np.asarray(got), ref, atol=1e-6)

    def test_block_rows_respects_byte_budget(self):
        # bench-shape NCHW activation (rows=2048, L=12544): 256 rows
        # would be 12.8 MB — the cap must pick a block under budget
        br = fused_epilogue._block_rows(2048, 12544)
        assert br is not None
        assert br * 12544 * 4 <= fused_epilogue._BLOCK_BYTE_BUDGET

    def test_kernel_marks_trace_collector(self):
        """The epilogue registers with the same trace collector the
        optimizer kernels use, so a step program containing it is
        flagged for step_flops' reference-twin accounting; the
        over-budget reference fallback marks nothing (no custom call
        to account for)."""
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 4, 8, 8).astype(np.float32))
        sc = np.ones(4, np.float32)
        sh = np.zeros(4, np.float32)
        sink = []
        with fused_optim.trace_collector(sink):
            fused_epilogue.scale_shift_relu(x, sc, sh, layout="NCHW")
        assert sink == ["epilogue"]
        big = jnp.asarray(
            rng.randn(1, 2, 512, 512).astype(np.float32))
        sink2 = []
        with fused_optim.trace_collector(sink2):
            fused_epilogue.scale_shift_relu(big, np.ones(2, np.float32),
                                            np.zeros(2, np.float32),
                                            layout="NCHW")
        assert sink2 == []

    def test_fold_bn_is_f32(self):
        s2, b2 = fused_epilogue.fold_bn(
            np.ones(4, np.float32), np.zeros(4, np.float32),
            np.zeros(4, np.float32), np.ones(4, np.float32), 1e-5)
        assert s2.dtype == jnp.float32 and b2.dtype == jnp.float32

    def _bn_relu_net(self):
        class Net(model.Model):
            def __init__(self):
                super().__init__()
                self.conv = layer.Conv2d(8, 3)
                self.bn = layer.BatchNorm2d()
                self.relu = layer.ReLU()

            def forward(self, x):
                return self.relu(self.bn(self.conv(x)))

        dev = device.create_cpu_device()
        dev.SetRandSeed(3)
        rng = np.random.RandomState(0)
        x = rng.randn(2, 4, 16, 16).astype(np.float32)
        tx = tensor.Tensor(data=x, device=dev, requires_grad=False)
        net = Net()
        net.compile([tx], is_train=False, use_graph=True)
        net.eval()
        net.bn.running_mean.data = jnp.asarray(
            rng.randn(8).astype(np.float32))
        net.bn.running_var.data = jnp.asarray(
            (rng.rand(8) + 0.5).astype(np.float32))
        return net, dev, x, tx

    def test_peephole_matches_reference_eval(self):
        net, dev, x, tx = self._bn_relu_net()
        ref = np.asarray(net(tx).data)      # eager: peephole inactive

        def fwd(arr):
            return net.forward(tensor.Tensor(
                data=arr, device=dev, requires_grad=False)).data

        with fused_epilogue.enabled_scope(True):
            got = np.asarray(jax.jit(fwd)(jnp.asarray(x)))
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_peephole_off_by_default(self):
        assert not fused_epilogue.enabled()
        net, dev, x, tx = self._bn_relu_net()

        # with the gate off, even a traced eval keeps the reference ops
        captured = []
        orig = fused_epilogue.try_relu_epilogue

        def spy(t):
            r = orig(t)
            captured.append(r is not None)
            return r

        fused_epilogue.try_relu_epilogue = spy
        try:
            def fwd(arr):
                return net.forward(tensor.Tensor(
                    data=arr, device=dev, requires_grad=False)).data
            jax.jit(fwd)(jnp.asarray(x))
        finally:
            fused_epilogue.try_relu_epilogue = orig
        assert captured and not any(captured)

    def test_frozen_stats_training_declines(self):
        """freeze_stats BN in TRAINING mode still backprops through
        scale/bias: its output carries the tag (it runs the inference
        op) but the peephole must decline while training, or the fused
        output would silently drop those gradients."""
        from singa_tpu.autograd_base import CTX
        net, dev, x, tx = self._bn_relu_net()
        net.bn.freeze_stats = True
        y = net.bn(net.conv(tx))
        assert getattr(y, "_bn_epilogue", None) is not None
        prev = CTX.training
        CTX.training = True
        try:
            with fused_epilogue.enabled_scope(True):
                assert fused_epilogue.try_relu_epilogue(y) is None
        finally:
            CTX.training = prev

    def test_training_mode_bn_carries_no_tag(self):
        # training-mode BN outputs carry no folding tag, so the
        # peephole structurally cannot fire mid-training
        net, dev, x, tx = self._bn_relu_net()
        net.train()
        try:
            y = net.bn(net.conv(tx))
            assert getattr(y, "_bn_epilogue", None) is None
        finally:
            net.eval()

    # -- conv→BN→add→ReLU residual tail --------------------------------

    @pytest.mark.parametrize("layout,shape", [("NCHW", (2, 5, 7, 7)),
                                              ("NHWC", (2, 7, 7, 5))])
    def test_scale_shift_add_relu_parity(self, layout, shape):
        rng = np.random.RandomState(2)
        x = rng.randn(*shape).astype(np.float32)
        r = rng.randn(*shape).astype(np.float32)
        C = shape[1] if layout == "NCHW" else shape[-1]
        sc = (rng.rand(C) + 0.5).astype(np.float32)
        sh = rng.randn(C).astype(np.float32)
        got = fused_epilogue.scale_shift_add_relu(
            jnp.asarray(x), sc, sh, jnp.asarray(r), layout=layout)
        b = (1, C, 1, 1) if layout == "NCHW" else (1, 1, 1, C)
        ref = np.maximum(x * sc.reshape(b) + sh.reshape(b) + r, 0)
        assert got.dtype == x.dtype and got.shape == x.shape
        np.testing.assert_allclose(np.asarray(got), ref, atol=1e-6)

    def test_add_relu_budget_counts_both_tiles(self):
        """The residual kernel holds TWO full-size tiles per block, so
        the budgeted row block must halve (or fall back) relative to
        the plain kernel's — an unscaled budget would be Mosaic-doomed
        on real silicon at bench shapes."""
        one = fused_epilogue._block_rows(2048, 12544, 4, n_inputs=1)
        two = fused_epilogue._block_rows(2048, 12544, 4, n_inputs=2)
        assert two is not None and two * 2 * 12544 * 4 <= \
            fused_epilogue._BLOCK_BYTE_BUDGET
        assert two <= one
        # a shape where even the minimum block × 2 blows the budget
        # computes via the reference path (and marks nothing)
        assert fused_epilogue._block_rows(8, 180000, 4,
                                          n_inputs=2) is None
        rng = np.random.RandomState(1)
        x = rng.randn(1, 2, 400, 450).astype(np.float32)
        r = rng.randn(1, 2, 400, 450).astype(np.float32)
        sc = (rng.rand(2) + 0.5).astype(np.float32)
        sh = rng.randn(2).astype(np.float32)
        sink = []
        with fused_optim.trace_collector(sink):
            got = fused_epilogue.scale_shift_add_relu(
                jnp.asarray(x), sc, sh, jnp.asarray(r), layout="NCHW")
        ref = np.maximum(x * sc.reshape(1, 2, 1, 1)
                         + sh.reshape(1, 2, 1, 1) + r, 0)
        np.testing.assert_allclose(np.asarray(got), ref, atol=1e-6)

    def test_add_kernel_marks_trace_collector(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 4, 8, 8).astype(np.float32))
        r = jnp.asarray(rng.randn(2, 4, 8, 8).astype(np.float32))
        sink = []
        with fused_optim.trace_collector(sink):
            fused_epilogue.scale_shift_add_relu(
                x, np.ones(4, np.float32), np.zeros(4, np.float32), r,
                layout="NCHW")
        assert sink == ["epilogue"]

    def _residual_net(self, downsample=False):
        """conv→BN→add→ReLU residual block; ``downsample=True`` runs
        the skip branch through its own conv+BN (BOTH add operands
        tagged — the downsample-block shape)."""
        class Net(model.Model):
            def __init__(self):
                super().__init__()
                self.conv = layer.Conv2d(8, 3, padding=1)
                self.bn = layer.BatchNorm2d()
                self.down = layer.Conv2d(8, 1) if downsample else None
                self.bn_d = layer.BatchNorm2d() if downsample else None
                self.add = layer.Add()
                self.relu = layer.ReLU()

            def forward(self, x):
                out = self.bn(self.conv(x))
                res = self.bn_d(self.down(x)) if self.down else x
                return self.relu(self.add(out, res))

        dev = device.create_cpu_device()
        dev.SetRandSeed(3)
        rng = np.random.RandomState(7)
        x = rng.randn(2, 8, 16, 16).astype(np.float32)
        tx = tensor.Tensor(data=x, device=dev, requires_grad=False)
        net = Net()
        net.compile([tx], is_train=False, use_graph=True)
        net.eval()
        net.bn.running_mean.data = jnp.asarray(
            rng.randn(8).astype(np.float32))
        net.bn.running_var.data = jnp.asarray(
            (rng.rand(8) + 0.5).astype(np.float32))
        if downsample:
            net.bn_d.running_mean.data = jnp.asarray(
                rng.randn(8).astype(np.float32))
            net.bn_d.running_var.data = jnp.asarray(
                (rng.rand(8) + 0.5).astype(np.float32))
        return net, dev, x, tx

    @pytest.mark.parametrize("downsample", [False, True])
    def test_residual_peephole_matches_reference_eval(self, downsample):
        net, dev, x, tx = self._residual_net(downsample)
        ref = np.asarray(net(tx).data)      # eager: peephole inactive

        def fwd(arr):
            return net.forward(tensor.Tensor(
                data=arr, device=dev, requires_grad=False)).data

        with fused_epilogue.enabled_scope(True):
            got = np.asarray(jax.jit(fwd)(jnp.asarray(x)))
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_residual_peephole_fires_in_trace(self):
        """The add output carries the residual tag, and the consuming
        relu actually takes the fused path inside a jit when enabled
        (the collector sees the epilogue mark)."""
        net, dev, x, tx = self._residual_net()
        y = net.add(net.bn(net.conv(tx)), tx)
        assert getattr(y, "_bn_add_epilogue", None) is not None

        def fwd(arr):
            return net.forward(tensor.Tensor(
                data=arr, device=dev, requires_grad=False)).data

        sink = []
        with fused_epilogue.enabled_scope(True), \
                fused_optim.trace_collector(sink):
            jax.jit(fwd)(jnp.asarray(x))
        assert "epilogue" in sink

    def test_residual_declines_in_training(self):
        """The residual branch backprops too: the peephole must
        decline in training mode exactly like the plain tail."""
        from singa_tpu.autograd_base import CTX
        net, dev, x, tx = self._residual_net()
        net.bn.freeze_stats = True
        y = net.add(net.bn(net.conv(tx)), tx)
        assert getattr(y, "_bn_add_epilogue", None) is not None
        prev = CTX.training
        CTX.training = True
        try:
            with fused_epilogue.enabled_scope(True):
                assert fused_epilogue.try_relu_epilogue(y) is None
        finally:
            CTX.training = prev

    def test_broadcast_residual_declines(self):
        """A skip connection that broadcasts (shape mismatch) is not
        the tail the kernel fuses — the peephole returns None and the
        reference add+relu runs."""
        net, dev, x, tx = self._residual_net()
        bn_out = net.bn(net.conv(tx))
        small = tensor.Tensor(data=np.ones((1, 8, 1, 1), np.float32),
                              device=dev, requires_grad=False)
        y = net.add(bn_out, small)
        assert getattr(y, "_bn_add_epilogue", None) is not None

        def probe(arr):
            yy = net.add(net.bn(net.conv(tensor.Tensor(
                data=arr, device=dev, requires_grad=False))), small)
            with fused_epilogue.enabled_scope(True):
                return fused_epilogue.try_relu_epilogue(yy) is None

        import jax as _jax
        assert bool(_jax.jit(probe)(jnp.asarray(x)))
