"""Continuous performance observability (observability/perf,
observability/trace_export, and their wiring through the model, the
resilient trainer, and the serving engine).

The PR's load-bearing acceptance criteria, pinned here:

- with the sampling profiler, HBM gauges, and request tracing ALL
  enabled, ``compiled_step_info()["n_traces"]`` stays 1 across a
  fixed-shape training loop and ≥3 serving slot refills, and the
  measured non-sample-step overhead is bounded;
- a forced shape change on a compiled step leaves a ``retrace`` event
  in the flight recorder naming the argument whose signature changed
  (old vs new shape/dtype), and compile wall-time lands in the
  ``compile_seconds`` histogram;
- ``trace_export`` renders a train-and-serve recorder ring into a
  schema-valid Chrome-trace JSON in which one gateway request's
  records (queue → prefill → decode ticks → delivery) share its
  request id.
"""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

from singa_tpu import device, layer, model, opt, tensor
from singa_tpu.models import transformer
from singa_tpu.observability import (export, metrics, perf, spans,
                                     trace_export)
from singa_tpu.resilience import FaultPlan, ResilientTrainer
from singa_tpu.tensor import Tensor


@pytest.fixture
def reg():
    return metrics.MetricsRegistry()


@pytest.fixture(autouse=True)
def _clean_recorder():
    spans.recorder().clear()
    yield
    spans.recorder().clear()
    spans.recorder().detach_jsonl()


class MLP(model.Model):
    def __init__(self):
        super().__init__()
        self.fc1 = layer.Linear(16)
        self.relu = layer.ReLU()
        self.fc2 = layer.Linear(4)
        self.loss_fn = layer.SoftMaxCrossEntropy()

    def forward(self, x):
        return self.fc2(self.relu(self.fc1(x)))

    def train_one_batch(self, x, y):
        out = self.forward(x)
        loss = self.loss_fn(out, y)
        self.optimizer(loss)
        return out, loss


def _compiled_mlp(batch=16, seed=7):
    dev = device.create_cpu_device()
    dev.SetRandSeed(seed)
    rng = np.random.RandomState(0)
    x = rng.randn(batch, 8).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, batch)]
    tx = tensor.Tensor(data=x, device=dev, requires_grad=False)
    ty = tensor.Tensor(data=y, device=dev, requires_grad=False)
    m = MLP()
    m.set_optimizer(opt.SGD(lr=0.1, momentum=0.9))
    m.compile([tx], is_train=True, use_graph=True)
    return m, tx, ty


def _batch(dev, batch):
    rng = np.random.RandomState(1)
    x = rng.randn(batch, 8).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, batch)]
    return (tensor.Tensor(data=x, device=dev, requires_grad=False),
            tensor.Tensor(data=y, device=dev, requires_grad=False))


# ---------------------------------------------------------------------------
# HBM telemetry
# ---------------------------------------------------------------------------

class _FakeDevice:
    def __init__(self, stats):
        self._stats = stats
        self.calls = 0

    def memory_stats(self):
        self.calls += 1
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


class TestHbm:
    def test_stats_normalized(self):
        d = _FakeDevice({"bytes_in_use": 10, "peak_bytes_in_use": 2**30,
                         "bytes_limit": 2**31, "largest_alloc_size": 7,
                         "irrelevant": "x"})
        s = perf.hbm_stats(d)
        assert s["bytes_in_use"] == 10
        assert s["peak_bytes_in_use"] == 2**30
        assert s["peak_gib"] == 1.0
        assert s["largest_alloc_size"] == 7
        assert "irrelevant" not in s

    @pytest.mark.parametrize("dev", [
        None, object(), _FakeDevice(None), _FakeDevice({}),
        _FakeDevice(RuntimeError("no stats"))])
    def test_unusable_stats_are_none(self, dev):
        assert perf.hbm_stats(dev) is None

    def test_raise_errors_keeps_the_diagnostic(self):
        """Diagnostic callers (the HBM probe children) must see WHY the
        read failed, not the same None a stats-less CPU produces."""
        with pytest.raises(RuntimeError, match="driver wedged"):
            perf.hbm_stats(_FakeDevice(RuntimeError("driver wedged")),
                           raise_errors=True)
        # a backend with no memory_stats attribute is still just None
        assert perf.hbm_stats(object(), raise_errors=True) is None

    def test_record_hbm_sets_gauges(self, reg):
        d = _FakeDevice({"bytes_in_use": 100, "peak_bytes_in_use": 200,
                         "bytes_limit": 300, "pool_bytes": 40})
        s = perf.record_hbm(d, reg, site="train")
        assert s["bytes_in_use"] == 100
        assert reg.get("hbm_bytes_in_use").value(site="train") == 100
        assert reg.get("hbm_peak_bytes_in_use").value(site="train") == 200
        assert reg.get("hbm_bytes_limit").value(site="train") == 300
        assert reg.get("hbm_stat_bytes").value(
            site="train", kind="pool_bytes") == 40

    def test_unavailable_device_probed_once(self, reg):
        d = _FakeDevice(None)
        assert perf.record_hbm(d, reg) is None
        assert perf.record_hbm(d, reg) is None
        assert d.calls == 1             # second call was a set lookup

    def test_live_array_report_groups_by_shape(self):
        import jax.numpy as jnp
        keep = jnp.zeros((33, 7), jnp.float32)      # noqa: F841
        # unbounded top: a full-suite session holds MANY bigger live
        # arrays, and the tiny probe must still be findable
        rep = perf.live_array_report(top=10**6)
        assert rep is not None and rep["n_arrays"] >= 1
        assert rep["total_bytes"] > 0
        assert any(r["shape"] == [33, 7] and r["dtype"] == "float32"
                   for r in rep["top"]), rep["top"][:5]
        # JSON-able: it rides blackbox dump headers
        json.dumps(rep)


# ---------------------------------------------------------------------------
# compile / retrace attribution
# ---------------------------------------------------------------------------

class TestCompileAttribution:
    def test_signature_and_diff(self):
        a = perf.step_signature([np.zeros((16, 8), np.float32),
                                 np.zeros((16, 4), np.float32)])
        b = perf.step_signature([np.zeros((12, 8), np.float32),
                                 np.zeros((16, 4), np.float16)])
        d = perf.diff_signatures(a, b)
        assert d == [
            {"arg": "arg0", "old": [[16, 8], "float32"],
             "new": [[12, 8], "float32"]},
            {"arg": "arg1", "old": [[16, 4], "float32"],
             "new": [[16, 4], "float16"]}]
        assert perf.diff_signatures(a, a) == []
        # appearing/vanishing args are named too
        assert perf.diff_signatures(a[:1], a)[0]["old"] is None

    def test_record_compile_first_vs_retrace(self, reg):
        sig1 = perf.step_signature([np.zeros((4, 2))])
        perf.record_compile("p", 0.5, sig1, registry=reg)
        sig2 = perf.step_signature([np.zeros((6, 2))])
        perf.record_compile("p", 0.25, sig2, prev_signature=sig1,
                            registry=reg)
        h = reg.get("compile_seconds")
        assert h.summary(program="p", source="fresh")["count"] == 2
        names = [r["name"] for r in spans.recorder().records()]
        assert names == ["compile", "retrace"]
        retrace = spans.recorder().records()[-1]
        assert retrace["changed"][0]["arg"] == "arg0"

    def test_identical_signature_relower_is_not_a_retrace(self, reg):
        sig = perf.step_signature([np.zeros((4, 2))])
        perf.record_compile("p", 0.1, sig, prev_signature=sig,
                            registry=reg)
        (rec,) = spans.recorder().records()
        assert rec["name"] == "compile"     # nothing changed: no alarm

    def test_forced_shape_change_leaves_retrace_event(self):
        """Acceptance: a forced shape change on a compiled step leaves
        a retrace event NAMING the changed argument (old vs new
        shape/dtype), and compile wall-time lands in the
        compile_seconds histogram."""
        m, tx, ty = _compiled_mlp(batch=16)
        for _ in range(3):
            m(tx, ty)                   # abstract first call + compiled
        tx2, ty2 = _batch(m.dev, 12)    # forced batch-shape change
        m(tx2, ty2)
        recs = spans.recorder().records()
        compiles = [r for r in recs if r["name"] == "compile"
                    and r.get("program") == "train_step"]
        retraces = [r for r in recs if r["name"] == "retrace"
                    and r.get("program") == "train_step"]
        assert compiles, recs
        assert retraces, recs
        (rt,) = retraces
        changed = {c["arg"]: c for c in rt["changed"]}
        assert changed["arg0"]["old"] == [[16, 8], "float32"]
        assert changed["arg0"]["new"] == [[12, 8], "float32"]
        assert changed["arg1"]["old"][0] == [16, 4]
        assert rt["compile_s"] > 0
        h = metrics.default_registry().get("compile_seconds")
        assert h.summary(program="train_step",
                         source="fresh")["count"] >= 2

    def test_fixed_shapes_record_exactly_one_compile(self):
        m, tx, ty = _compiled_mlp()
        for _ in range(5):
            m(tx, ty)
        recs = [r for r in spans.recorder().records()
                if r.get("program") == "train_step"]
        assert len(recs) == 1 and recs[0]["name"] == "compile"
        assert m.compiled_step_info()["n_traces"] == 1


# ---------------------------------------------------------------------------
# sampling profiler + anomaly sentinel (unit)
# ---------------------------------------------------------------------------

class TestSamplingProfiler:
    def test_cadence_and_force(self, reg):
        p = perf.SamplingProfiler(every=3, registry=reg)
        assert [s for s in range(10) if p.should_sample(s)] == [3, 6, 9]
        off = perf.SamplingProfiler(every=0, registry=reg)
        assert not any(off.should_sample(s) for s in range(10))
        off.force_next()
        assert off.should_sample(4)     # one-shot arm
        off.record(4, {"fusion.1": (2, 0.004)})
        assert not off.should_sample(5)

    def test_record_refreshes_gauges_and_event(self, reg):
        p = perf.SamplingProfiler(every=2, registry=reg)
        p.record(6, {"fusion.1": (2, 0.004), "dot.2": (1, 0.001)},
                 capture_s=0.05)
        assert reg.get("profile_samples_total").value() == 1
        assert reg.get("profile_last_sample_step").value() == 6
        assert reg.get("profile_fusion_seconds").value(
            fusion="fusion.1") == 0.004
        assert reg.get("profile_capture_seconds").summary()["count"] == 1
        (ev,) = spans.recorder().records()
        assert ev["name"] == "profile.sample" and ev["step"] == 6
        assert ev["top"][0][0] == "fusion.1"


class TestAnomalySentinel:
    def test_sustained_spike_fires_once(self, reg):
        s = perf.AnomalySentinel(factor=3.0, sustain=3, warmup=5,
                                 cooldown=10, registry=reg)
        fired = [s.observe(i, 0.01) for i in range(20)]
        assert not any(fired)
        fired = [s.observe(20 + i, 0.2) for i in range(5)]
        assert fired.count(True) == 1   # cooldown holds later spikes
        assert reg.get("perf_anomalies_total").value() == 1
        (ev,) = [r for r in spans.recorder().records()
                 if r["name"] == "step_anomaly"]
        assert ev["step_s"] == pytest.approx(0.2)
        # the spike-clipped EMA drifts only slowly: the recorded
        # baseline stays far below the spike it fired on
        assert ev["baseline_s"] < 0.05

    def test_single_blip_does_not_fire(self, reg):
        s = perf.AnomalySentinel(factor=3.0, sustain=3, warmup=5,
                                 registry=reg)
        for i in range(20):
            assert not s.observe(i, 0.5 if i == 12 else 0.01)
        assert reg.get("perf_anomalies_total").value() == 0

    def test_baseline_tracks_regime_change(self, reg):
        s = perf.AnomalySentinel(factor=3.0, sustain=3, warmup=5,
                                 cooldown=0, registry=reg)
        for i in range(30):
            s.observe(i, 0.01)
        for i in range(100):
            s.observe(30 + i, 0.02)     # legitimately slower now
        assert reg.get("perf_step_baseline_seconds").value() == \
            pytest.approx(0.02, rel=0.2)

    def test_straggler_attribution_rides_heartbeat_aggregation(self):
        def one(mean, count=20):
            return {"step_time": {"count": count, "sum": mean * count,
                                  "min": mean, "max": mean,
                                  "mean": mean},
                    "wire_errors": 0}
        agg = metrics.aggregate_summaries(
            {0: one(0.010), 1: one(0.011), 2: one(0.050), 3: one(0.012)})
        assert agg["step_time_stragglers"] == [2]
        # a fleet of one never names itself a straggler
        agg1 = metrics.aggregate_summaries({0: one(0.05)})
        assert agg1["step_time_stragglers"] == []


# ---------------------------------------------------------------------------
# trainer wiring: n_traces pin, overhead bound, anomaly end-to-end
# ---------------------------------------------------------------------------

class TestTrainerWiring:
    def test_everything_on_keeps_n_traces_at_one(self, tmp_path):
        """Acceptance (training half): sampling profiler + HBM gauges +
        the full telemetry bundle on, fixed shapes — the compiled step
        traced exactly once, and profile samples actually happened."""
        reg = metrics.default_registry()
        before = reg.counter("profile_samples_total").value()
        steps_before = reg.counter("train_steps_total").value()
        hist_before = reg.histogram(
            "train_step_seconds").summary()["count"]
        m, tx, ty = _compiled_mlp()
        tr = ResilientTrainer(m, str(tmp_path / "run"),
                              save_interval_steps=2, verbose=False,
                              profile_every=2)
        try:
            s = tr.run([(tx, ty)], num_steps=7)
        finally:
            tr.close()
        assert s["steps_run"] == 7
        assert m.compiled_step_info()["n_traces"] == 1
        assert reg.counter("profile_samples_total").value() == before + 3
        # every step counts, but the 3 PROFILED steps' inflated wall
        # (trace dump + parse) stays OUT of the step-time series — the
        # dashboards must not read sampling overhead as a regression
        assert reg.counter("train_steps_total").value() == \
            steps_before + 7
        assert reg.histogram("train_step_seconds").summary()["count"] \
            == hist_before + 4
        g = reg.get("profile_fusion_seconds")
        assert g is not None and g.to_doc()["series"], \
            "sampling profiler recorded no fusion rows"
        # the per-fusion samples left profile.sample events behind
        assert any(r["name"] == "profile.sample"
                   for r in spans.recorder().records())

    def test_failed_profiled_attempt_does_not_leak_the_flag(
            self, tmp_path):
        """A profiled attempt that dies after arming the exclusion
        flag must not drop the NEXT step from the step-time series:
        the flag is cleared per attempt, and the retried sample still
        counts."""
        reg = metrics.default_registry()
        hist_before = reg.histogram(
            "train_step_seconds").summary()["count"]
        m, tx, ty = _compiled_mlp()
        real = m.profile_step
        fails = {"left": 1}

        def flaky(*args, **kw):
            if fails["left"]:
                fails["left"] -= 1
                raise RuntimeError("transient profiler failure")
            return real(*args, **kw)

        m.profile_step = flaky
        tr = ResilientTrainer(m, str(tmp_path / "run"),
                              save_interval_steps=3, verbose=False,
                              profile_every=2, step_retries=2,
                              backoff_base=0.0)
        try:
            s = tr.run([(tx, ty)], num_steps=5)
        finally:
            tr.close()
        assert s["steps_run"] == 5 and s["step_retries"] == 1
        # steps 2 and 4 sampled (step 2's first attempt failed, the
        # retry profiled again) → 3 of 5 land in the histogram
        assert reg.histogram("train_step_seconds").summary()["count"] \
            == hist_before + 3

    def test_anomaly_sentinel_end_to_end(self, tmp_path):
        """A sustained injected stall fires the sentinel: attributed
        event, blackbox dump, and a one-shot profile capture on the
        next step."""
        reg = metrics.default_registry()
        samples0 = reg.counter("profile_samples_total").value()
        m, tx, ty = _compiled_mlp()
        plan = FaultPlan()
        for s in (10, 11, 12):
            plan.hang_step(s, seconds=0.4)
        tr = ResilientTrainer(m, str(tmp_path / "run"),
                              save_interval_steps=4, verbose=False,
                              faults=plan, anomaly_factor=3.0,
                              anomaly_sustain=3, anomaly_warmup=4)
        try:
            summary = tr.run([(tx, ty)], num_steps=15)
        finally:
            tr.close()
        assert summary["steps_run"] == 15
        events = [r for r in spans.recorder().records()
                  if r["name"] == "step_anomaly"]
        assert events, "sentinel never fired"
        assert events[0]["step_s"] >= 0.3
        # the blackbox landed with the step_anomaly reason
        bb = os.path.join(str(tmp_path / "run"), "telemetry",
                          "blackbox-0.jsonl")
        with open(bb) as f:
            head = json.loads(f.readline())
        assert head["reason"] == "step_anomaly"
        # and the forced one-shot capture ran on a later step
        assert reg.counter("profile_samples_total").value() > samples0

    def test_crash_blackbox_carries_live_array_breakdown(self,
                                                         tmp_path):
        """The OOM/crash post-mortem: a step that dies past the retry
        budget leaves a blackbox whose header names the error and the
        live-array allocation breakdown."""
        m, tx, ty = _compiled_mlp()
        plan = FaultPlan().fail_step(step=3, times=10)
        tr = ResilientTrainer(m, str(tmp_path / "run"), verbose=False,
                              faults=plan, step_retries=1,
                              backoff_base=0.0)
        try:
            with pytest.raises(Exception, match="injected step"):
                tr.run([(tx, ty)], num_steps=6)
        finally:
            tr.close()
        bb = os.path.join(str(tmp_path / "run"), "telemetry",
                          "blackbox-0.jsonl")
        with open(bb) as f:
            head = json.loads(f.readline())
        assert head["reason"] == "crash"
        assert "injected step" in head["extra"]["error"]
        assert head["extra"]["live_arrays"]["n_arrays"] >= 1

    def test_non_sample_step_overhead_bounded(self, reg):
        """Acceptance: the measured per-step cost of EVERYTHING this PR
        adds to a non-sample step — the sampling check, the sentinel,
        and the HBM probe fast path — stays far under a millisecond
        (mirrors PR 6's instrumentation-overhead bound)."""
        profiler = perf.SamplingProfiler(every=1000, registry=reg)
        sentinel = perf.AnomalySentinel(factor=3.0, registry=reg)
        no_stats_dev = object()
        perf.record_hbm(no_stats_dev, reg)      # pay the one probe
        n = 300
        t0 = time.perf_counter()
        for i in range(n):
            profiler.should_sample(i)
            sentinel.observe(i, 0.001)
            perf.record_hbm(no_stats_dev, reg)
        per_step = (time.perf_counter() - t0) / n
        assert per_step < 500e-6, f"{per_step * 1e6:.1f} µs per step"


# ---------------------------------------------------------------------------
# open spans (satellite): start timestamps + in-flight spans in dumps
# ---------------------------------------------------------------------------

class TestOpenSpans:
    def test_span_records_carry_start_timestamp(self):
        with spans.span("step", step=1):
            time.sleep(0.002)
        (rec,) = spans.recorder().records()
        assert rec["ts_start"] <= rec["ts"]
        assert rec["ts"] - rec["ts_start"] == pytest.approx(
            rec["dur_s"], abs=0.05)

    def test_open_spans_visible_while_inside(self):
        assert spans.open_spans() == []
        with spans.context(rank=3):
            with spans.span("restore", step=9):
                (o,) = spans.open_spans()
                assert o["kind"] == "span_open"
                assert o["name"] == "restore" and o["step"] == 9
                assert o["rank"] == 3       # ambient context captured
                assert o["age_s"] >= 0
        assert spans.open_spans() == []

    def test_dump_includes_inflight_spans(self, tmp_path, reg):
        """The satellite's contract: a blackbox written while a span is
        still open shows what the process was INSIDE when it died."""
        rec = spans.FlightRecorder(capacity=8)
        s = spans.span("step", step=42)
        s.__enter__()
        try:
            path = rec.dump(str(tmp_path / "bb.jsonl"), reason="hang",
                            registry=reg)
        finally:
            s.__exit__(None, None, None)
        lines = [json.loads(ln) for ln in open(path)]
        opens = [ln for ln in lines if ln.get("kind") == "span_open"]
        assert len(opens) == 1
        assert opens[0]["name"] == "step" and opens[0]["step"] == 42
        assert "ts_start" in opens[0]


# ---------------------------------------------------------------------------
# serving: per-request traces + refill pin + gateway /trace.json
# ---------------------------------------------------------------------------

DEV = device.create_cpu_device()


def _tiny_engine(slots=2, **kw):
    np.random.seed(0)
    m = transformer.TransformerLM(19, d_model=16, n_heads=2,
                                  n_layers=2, max_len=64, tp=False)
    m.eval()
    m(Tensor(data=np.zeros((1, 4), np.float32), device=DEV,
             requires_grad=False))
    return m.compile_serving(slots=slots, max_len=32, prefill_len=8,
                             registry=metrics.MetricsRegistry(), **kw)


class TestServingRequestTraces:
    def test_refills_keep_n_traces_one_and_trace_requests(self):
        """Acceptance (serving half): request tracing on, ≥3 slot
        refills — the decode program still traced exactly once, and
        every request's records (queued → prefill → decode ticks →
        delivery) share its trace id."""
        eng = _tiny_engine(slots=2)
        rng = np.random.RandomState(0)
        futs = [eng.submit(rng.randint(1, 19, (3,)),
                           max_new_tokens=int(rng.randint(2, 5)),
                           trace_id=f"t-{i}")
                for i in range(8)]
        eng.run_until_idle()
        for f in futs:
            f.result(timeout=5)
        info = eng.compiled_step_info()
        assert info["n_traces"] == 1, info
        # 8 prompts through 2 slots = at least 6 refills
        assert eng._reg.get("serve_prefill_total").total() == 8

        recs = spans.recorder().records()
        by_req = {}
        for r in recs:
            if r.get("request"):
                by_req.setdefault(r["request"], []).append(r["name"])
        assert set(by_req) == {f"t-{i}" for i in range(8)}
        for rid, names in by_req.items():
            assert names[0] == "request.queued", (rid, names)
            assert "request.prefill" in names, (rid, names)
            assert "request.decode_tick" in names, (rid, names)
            assert names[-1] == "request.delivered", (rid, names)
        # serve-program compile attribution fired once per program
        progs = [r.get("program") for r in recs
                 if r["name"] == "compile"]
        assert progs.count("serve_prefill") == 1
        assert progs.count("serve_decode") == 1
        eng.stop()

    def test_trace_requests_off_records_nothing(self):
        eng = _tiny_engine(slots=2, trace_requests=False)
        fut = eng.submit([1, 2, 3], max_new_tokens=2)
        eng.run_until_idle()
        fut.result(timeout=5)
        assert not any(r.get("request")
                       for r in spans.recorder().records())
        eng.stop()

    def test_exported_ring_is_schema_valid_with_request_lanes(self):
        """Acceptance: the train-and-serve ring renders into a
        schema-valid Chrome trace where one request's events share a
        tid (its timeline lane)."""
        m, tx, ty = _compiled_mlp()
        for _ in range(3):
            m(tx, ty)                       # training records
        eng = _tiny_engine(slots=2)
        fut = eng.submit([1, 2, 3], max_new_tokens=3, trace_id="r-77")
        eng.run_until_idle()
        fut.result(timeout=5)
        eng.stop()
        doc = trace_export.validate_chrome_trace(
            trace_export.to_chrome_trace(
                spans.recorder().records() + spans.open_spans()))
        evs = [e for e in doc["traceEvents"]
               if e.get("args", {}).get("request") == "r-77"]
        names = [e["name"] for e in evs]
        assert "request.queued" in names
        assert "request.decode_tick" in names
        assert "request.delivered" in names
        assert len({e["tid"] for e in evs}) == 1, evs

    def test_gateway_mints_request_id_and_serves_trace(self):
        """End to end through HTTP: the gateway mints the request id,
        echoes it in the response, and /trace.json serves a
        schema-valid Chrome trace containing that request's lane."""
        from singa_tpu.serving import serve_gateway
        eng = _tiny_engine(slots=2).start()
        server, port = serve_gateway(eng)
        try:
            body = json.dumps({"prompt": [1, 2, 3],
                               "max_new_tokens": 3}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/generate", data=body,
                headers={"Content-Type": "application/json"})
            doc = json.loads(urllib.request.urlopen(
                req, timeout=30).read())
            rid = doc["request_id"]
            assert rid and doc["tokens"]
            trace = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/trace.json",
                timeout=30).read())
            trace_export.validate_chrome_trace(trace)
            mine = [e for e in trace["traceEvents"]
                    if e.get("args", {}).get("request") == rid]
            assert {e["name"] for e in mine} >= {
                "request.queued", "request.prefill",
                "request.delivered"}
            # the live trace closes with the metrics snapshot (fusion
            # tables ride it), like a blackbox export would
            assert any(e["name"] == "metrics_snapshot"
                       for e in trace["traceEvents"])
            # an ERROR reply still echoes the request id — the failed
            # request's lane is the main debugging target
            bad = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/generate",
                data=json.dumps({"prompt": list(range(99)),
                                 "request_id": "dbg-1"}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(bad, timeout=30)
                raise AssertionError("oversized prompt accepted")
            except urllib.error.HTTPError as e:
                err = json.loads(e.read())
                assert e.code == 400
                assert err["request_id"] == "dbg-1", err
        finally:
            server.shutdown()
            server.server_close()
            eng.stop()


class TestTraceExportUnit:
    def test_empty_input_is_valid(self):
        doc = trace_export.to_chrome_trace([])
        trace_export.validate_chrome_trace(doc)

    def test_dump_and_metrics_land_on_the_recorder_row(self):
        """Process-global records (dump headers, metrics snapshots)
        must not be misattributed to whichever rank claimed the first
        pid — they get their own named 'recorder' process row."""
        doc = trace_export.to_chrome_trace([
            {"kind": "span", "name": "step", "rank": 1, "ts": 10.0,
             "ts_start": 9.9, "dur_s": 0.1},
            {"kind": "dump", "ts": 11.0, "reason": "preempted"},
        ])
        (span_ev,) = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        (dump_ev,) = [e for e in doc["traceEvents"]
                      if e["name"] == "blackbox_dump"]
        assert dump_ev["pid"] != span_ev["pid"]
        names = {e["args"]["name"]: e["pid"] for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        assert names["recorder"] == dump_ev["pid"]
        assert names["rank 1"] == span_ev["pid"]

    def test_pre_pr9_span_without_ts_start_still_renders(self):
        doc = trace_export.to_chrome_trace(
            [{"kind": "span", "name": "step", "ts": 10.0,
              "dur_s": 0.5}])
        (ev,) = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert ev["dur"] == pytest.approx(0.5e6)

    @pytest.mark.parametrize("bad, match", [
        ({"traceEvents": "nope"}, "not a list"),
        ({"traceEvents": [{"name": "x"}]}, "phase"),
        ({"traceEvents": [{"ph": "X", "name": "x", "pid": 1, "tid": 0,
                           "ts": 1.0}]}, "dur"),
        ({"traceEvents": [{"ph": "i", "name": "x", "pid": 1, "tid": 0,
                           "ts": -1.0}]}, "ts"),
    ])
    def test_validator_names_the_problem(self, bad, match):
        with pytest.raises(ValueError, match=match):
            trace_export.validate_chrome_trace(bad)

    def test_unserializable_args_fail_at_validate(self):
        doc = trace_export.to_chrome_trace(
            [{"kind": "event", "name": "e", "ts": 1.0,
              "payload": object()}])
        with pytest.raises(ValueError, match="serializable"):
            trace_export.validate_chrome_trace(doc)
