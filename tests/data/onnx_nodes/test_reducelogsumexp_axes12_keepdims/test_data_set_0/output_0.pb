
Boutput_0Jµ]@$t@