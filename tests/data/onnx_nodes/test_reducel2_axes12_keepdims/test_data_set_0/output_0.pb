
Boutput_0J7W@=å@