
Binput_1J0:9;V2gg>ƚ?>nӼǿ&
?v\?$c?