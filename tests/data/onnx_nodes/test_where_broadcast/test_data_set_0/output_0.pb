
Boutput_0J0ì´¾˜.F¾¨X?s…?cÂï¿˜.F¾…´§?s…?g(<˜.F¾J}r?s…?