
Binput_2J§b>Y0š?Úi8@V¿