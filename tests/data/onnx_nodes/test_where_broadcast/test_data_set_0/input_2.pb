
Binput_2Jg(<ã•æ?J}r?Ñúï¼