
Binput_2Jj­•¾˜.F¾Š¯–?s…?