
Binput_1JPqtwC?(=E>wԯXT?: ѿ7ke?swu">Ζ?,#	qj>p{>&l*>