
Binput_2J0Z?h7$qtwC?(=E>wԯXT?