
Boutput_0JT±¿