
Boutput_0J”‹@