
Binput_1J$Ÿx\¿Ôt¿SžŒ?å¾h>P?%Sò>Wý@?M`c¿fäb¾