
Boutput_0J0i0?V}?ė[>ze?@?