
Boutput_0J‚Å‡?