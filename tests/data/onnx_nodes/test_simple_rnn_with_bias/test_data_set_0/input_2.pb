
Binput_2Jd d>SRg#=X>t>b>7?*[[q0>uO	ξ@Y>?|$#>pb?:8پ