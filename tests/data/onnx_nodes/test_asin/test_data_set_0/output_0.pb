
Boutput_0J0>S#q?>?o#)jF*>%NXY?