
Boutput_0J
…Î@hJA