
Boutput_0J0M>)>0=b@pF!?B?.X