
Binput_1J$X>u4.?wB2?lϽM