
Boutput_0J& i¿2s¿Ì]q¿