
Binput_2J0u	Kx>
$5a?~?z٨?3a