
Binput_1JP%?,@e(֤2OPr>AU?Do屾+пȿ2?/e??酪