
Binput_1JP]S=V?23DgKSpTf?Gʿy>?= >_w?FW>b/+5i>??UI