
Boutput_0J0)o@b>ܮ.@w?:?Y?-l@W?D