
Boutput_1J -jc?}Uø«P±øﬂB—=VG?c™?"_>÷‚(ø