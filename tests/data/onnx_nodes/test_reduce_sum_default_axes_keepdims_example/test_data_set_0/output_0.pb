
Boutput_0Jş	ÍÀ