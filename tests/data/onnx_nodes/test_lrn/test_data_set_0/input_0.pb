
Binput_0Jf`Cd1>	?>$Y><>0?"7L=p?^h?y+@My=wc>
Hƨ?*
us|'齜X<C-> /Te;_?)=I>4?dタ
Z?Ͻњe_Q?s>=