
Boutput_0J$Á—ö½óà@Iğ¾m5?¨½®¿O	ª¾Iâõ¾:Z½¿bFX¾