
Binput_2Jm5?óà@bFX¾Á—ö½:Z½¿O	ª¾