
Boutput_0Js·Ô¿b þ¿