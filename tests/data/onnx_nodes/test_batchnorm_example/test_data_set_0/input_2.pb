
Binput_2J9Åt>Ó
€¿*EÖ?