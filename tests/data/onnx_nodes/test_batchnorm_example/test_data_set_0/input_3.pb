
Binput_3JËo%>¦È?·_J¿