
Binput_4Ji"´?˜h9?¢o@