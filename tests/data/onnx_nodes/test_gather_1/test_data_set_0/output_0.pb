
Boutput_0J(g?i囿)?%>b>g>ǉ̽n