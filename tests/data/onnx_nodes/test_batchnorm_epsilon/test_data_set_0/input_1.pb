
Binput_1J8…?b[@ ?