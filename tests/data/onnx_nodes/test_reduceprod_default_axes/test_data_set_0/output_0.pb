
Boutput_0Jl·7®