
Boutput_0JyEÀ