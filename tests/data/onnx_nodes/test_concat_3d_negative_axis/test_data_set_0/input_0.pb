
Binput_0J0asɾ,]geQɿ|S?$lK>V[_qP