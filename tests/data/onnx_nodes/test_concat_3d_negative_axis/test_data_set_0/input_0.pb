
Binput_0J0ÈÉ¾,¬¾]g¹¿eQÉ¿ù|S?$’l½ÄK>›V¿[_ª¾qçP¾,Oå>âxA¿