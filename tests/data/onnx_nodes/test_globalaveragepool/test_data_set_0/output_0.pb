
Boutput_0JÛ¾@ÌÉ=øía¾