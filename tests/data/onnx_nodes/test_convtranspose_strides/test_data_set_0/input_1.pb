
Binput_1JHt?PӿT>ʿ(.X?A?>J>{@0GϿ?Cj?ȥi*q`?;鿳nξ