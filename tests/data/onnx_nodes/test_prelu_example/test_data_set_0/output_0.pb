
Boutput_0J01ƿl?rMz5B>}䗿o??E