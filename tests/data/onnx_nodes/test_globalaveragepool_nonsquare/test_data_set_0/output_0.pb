
Boutput_0JmKl>sBP=!>