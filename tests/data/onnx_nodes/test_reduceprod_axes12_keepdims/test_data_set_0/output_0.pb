
Boutput_0J²ãŠ¶şO)7