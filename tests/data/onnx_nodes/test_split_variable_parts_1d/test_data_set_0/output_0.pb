
Boutput_0J,@%zSt\>