
Binput_0J?>T> >