
Boutput_0JG}	:E{h.K>ཫaLP	RO	>fi'gk=IX>l?LУ|>EV>?Ϩ+oqtx