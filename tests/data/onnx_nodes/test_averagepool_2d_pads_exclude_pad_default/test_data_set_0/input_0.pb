
Binput_0Jـ@?[E C?OAɾi>dd`?Voe>k?{^꾙C>=24?_-?PXf>gNs@+@.>ߒ?9Y