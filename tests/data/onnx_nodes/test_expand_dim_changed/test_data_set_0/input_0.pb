
Binput_0J„
?Q®½v¿