
Boutput_0Jb ş¿