
Boutput_0JC®¦ÀÝ°^À