
Boutput_0JøKõ>	7G¿¤NÞ?w)¹¿q•Ê¿çu?