
Binput_1J?W?>