
Boutput_0J`gɿs?p="`/U6?[3=i%@>R&'qߜ[I>xݽaZ>wB96x>