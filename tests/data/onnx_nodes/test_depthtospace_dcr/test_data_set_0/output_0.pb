
Boutput_0J>4IH ?uGPu?exWӼP??D[+L;ﾉg>\\d/F?j]?BF̕!@I?]7,6K?%ƾ=?x 	>Nf
3>:wu$L#3[aRwlT??>