
Binput_0J>u+;@I?7K?:#34IH ?GPL],6%ƾwu$L[au?WӼ?g>d/F?BF=x 	>
R?exP?D[\\j]?̕!?Nf3>wlT?>