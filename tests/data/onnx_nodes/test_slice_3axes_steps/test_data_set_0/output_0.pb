
Boutput_0JHOj>=Vdk>Z>>0X?
="$ǰ෿$c??ǿ$Fm'=