
Binput_0J.>ߒ?9YzuhH2=de<5v$*Ұm8þݟU!?yBB&>z?,=޿XhM	 >az2Y=V[?j>޼>Oľ?G?9*x<!??տZ>\.>R؎?dk$eϿ?/$Q>^/˿
=aHX?<>0b
W?glh?i.3@(̦\,cc?"-	@X?7}K?_Y?pLmc?8#3>pa0N>$V>-Y1?)<>,Dᾖ෿g?ǰ֟k"$:9;V2gg>ƚ?>nӼǿ&
?v\?$c?b>Y0?i8@V[C]?q>=J>m',t$F