
Boutput_0JSI@