
Boutput_0J`>8M5=?(5I>P)8>c<pM<+<_t\=/EcA>u8>.>"k6=