
Boutput_0JvƂA