
Binput_0J 8ʿD?L=1bR' jpK>