
Binput_1Jt͵f@x>