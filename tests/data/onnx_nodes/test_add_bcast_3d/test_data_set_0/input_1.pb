
Binput_1Jy2>