
Binput_1JR'¾ ¾jž¾