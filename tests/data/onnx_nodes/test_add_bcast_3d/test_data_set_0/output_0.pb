
Boutput_0J`lLf[?ʾw/ЋG2Jd?	>[H>嬾C<XP<Dۨwu߁>>=F?