
Boutput_0J"²@