"""Fleet fault-tolerance matrix (CPU, fast tier): circuit-breaker
routing, deadline-budgeted exactly-once re-dispatch, and load
shedding.

- breaker transitions: closed → open after N consecutive failures
  (capped exponential backoff), skipped while open, ONE half-open
  probe re-admits (success closes and resets the ladder, failure
  re-opens with a doubled delay) — driven with a fake clock, so the
  cadence assertions are exact, not sleep-flaky;
- a crashed replica never kills routing while a survivor exists (the
  fleet.py:191 regression), and an unreadable queue depth sorts a
  replica LAST (the ``_depth`` → 0 regression);
- exactly-once re-dispatch: a crash-after-admit strands the request,
  the survivor's re-run is token-identical to an uninterrupted greedy
  run, and the late-original/double-delivery guard raises;
- retries never reset the clock: the re-dispatched attempt carries the
  REMAINING deadline budget, and a budget-exhausted request fails
  typed (``RequestTimeout`` → the gateway's 504) exactly once — never
  a silent hang;
- sustained backpressure sheds typed (``RequestShed`` + retry_after,
  the gateway's ``Retry-After`` header) with an optional brownout
  step-down first;
- gateway contracts: 413 body cap (missing/garbage/oversized
  Content-Length), one deadline for submit + wait, fleet-front
  ``/healthz``, and the breaker/re-dispatch/shed counters riding
  ``heartbeat_summary``.
"""

import http.client
import json
import socket
import threading
import time

import numpy as np
import pytest

from singa_tpu import device
from singa_tpu.models import transformer
from singa_tpu.observability import metrics as obs_metrics
from singa_tpu.resilience.faults import FaultPlan
from singa_tpu.serving import (BlockPoolExhausted, EngineDraining,
                               FleetRouter, QueueFull, ReplicaCrashed,
                               Request, RequestShed, RequestTimeout,
                               ServeFuture, ServingError,
                               ServingReplica, serve_gateway)
from singa_tpu.serving.fleet import (CircuitBreaker, ShedPolicy,
                                     brownout_shrink_generation)
from singa_tpu.serving.scheduler import budget_remaining, deadline_in
from singa_tpu.tensor import Tensor

pytestmark = pytest.mark.serving

DEV = device.create_cpu_device()


def _reg():
    return obs_metrics.MetricsRegistry()


@pytest.fixture(scope="module")
def lm():
    np.random.seed(0)
    m = transformer.TransformerLM(19, d_model=16, n_heads=2,
                                  n_layers=2, max_len=64, tp=False)
    m.eval()
    m(Tensor(data=np.zeros((1, 4), np.float32), device=DEV,
             requires_grad=False))
    return m


def _engine(lm, **kw):
    kw.setdefault("registry", _reg())
    return lm.compile_serving(slots=2, max_len=32, prefill_len=8,
                              **kw)


class _FakeReplica:
    """Replica stand-in with programmable submit behavior — the router
    mechanics (breakers, budgets, sheds) are host-side and must be
    testable without compiling an engine."""

    def __init__(self, name, behavior="ok", depth=0):
        self.name = name
        self.draining = False
        self.behavior = behavior
        self.depth = depth
        self.calls = 0
        self.last_kwargs = None
        self.futures = []

    def queue_depth(self):
        if self.depth == "raise":
            raise RuntimeError("queue unreadable")
        return self.depth

    def submit(self, *args, **kwargs):
        self.calls += 1
        self.last_kwargs = dict(kwargs)
        if self.behavior == "crashed":
            raise ReplicaCrashed("engine crashed (boom)")
        if self.behavior == "wire":
            raise ConnectionError("wire down")
        if self.behavior == "full":
            raise QueueFull("request queue at capacity")
        fut = ServeFuture()
        self.futures.append(fut)
        if self.behavior == "ok":
            fut.set_result({"tokens": [1, 2, 3], "prompt_len": 1,
                            "ttft_s": 0.0})
        return fut     # "blackhole": admitted, never fulfilled

    def health(self):
        return {"name": self.name, "status": "serving"}


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestCircuitBreaker:
    def test_open_halfopen_close_transitions_and_backoff_ladder(self):
        br = CircuitBreaker(threshold=2, backoff=0.5, backoff_cap=8.0)
        assert br.state == "closed" and br.admits(0.0)
        assert br.record_failure(0.0) is False
        assert br.state == "closed"          # below threshold
        assert br.record_failure(0.0) is True
        assert br.state == "open" and br.open_until == 0.5
        assert not br.admits(0.4)
        assert br.admits(0.6)                # backoff elapsed: ONE probe
        br.begin_probe(0.6)
        assert br.state == "half_open"
        assert not br.admits(0.6)            # probe slot is claimed
        br.record_failure(0.6)               # probe failed: doubled delay
        assert br.state == "open"
        assert br.open_until == pytest.approx(0.6 + 1.0)
        br.begin_probe(2.0)
        br.record_success(2.0)               # probe landed: re-admitted
        assert br.state == "closed"
        assert br.opens == 0 and br.consecutive_failures == 0

    def test_backoff_is_capped(self):
        br = CircuitBreaker(threshold=1, backoff=1.0, backoff_cap=4.0)
        for _ in range(10):
            br.record_failure(0.0)
        assert br.open_until == 4.0          # never past the cap


class TestDeadlineBudget:
    def test_helpers(self):
        assert deadline_in(None) is None
        assert budget_remaining(None) is None
        d = deadline_in(2.0, now=10.0)
        assert d == 12.0
        assert budget_remaining(d, now=10.5) == pytest.approx(1.5)
        assert budget_remaining(d, now=99.0) == 0.0   # floored
        assert deadline_in(0.0, now=3.0) == 3.0       # 0 = already due


class TestBreakerRouting:
    def test_crashed_replica_ejected_and_probed_on_backoff_only(self):
        """The tentpole cadence contract: 3 consecutive failures eject
        the replica; while open it receives ZERO traffic; after the
        backoff exactly ONE probe; a failed probe doubles the delay; a
        successful probe re-admits it."""
        clk = _FakeClock()
        r0, r1 = _FakeReplica("r0", "crashed"), _FakeReplica("r1")
        reg = _reg()
        rt = FleetRouter([r0, r1], registry=reg, breaker_threshold=3,
                         breaker_backoff=0.5, clock=clk)
        for _ in range(8):
            f = rt.submit([1], max_new_tokens=4)
            assert f.result(timeout=1)["tokens"] == [1, 2, 3]
            assert f.deliveries == 1
        # first 3 submits hit r0 (and fail over); then the breaker
        # opens and r0 is SKIPPED — not poisoned-through
        assert r0.calls == 3
        assert rt.breaker_states() == {"r0": "open", "r1": "closed"}
        assert reg.get("serve_fleet_breaker_state").value(
            replica="r0") == 2
        assert reg.get("serve_fleet_breaker_open_total").total() == 1
        # backoff elapsed: exactly ONE half-open probe, which fails
        clk.t = 0.6
        rt.submit([1]).result(timeout=1)
        assert r0.calls == 4
        assert reg.get("serve_fleet_probe_total").total() == 1
        # doubled backoff: no second probe until it elapses
        rt.submit([1]).result(timeout=1)
        assert r0.calls == 4
        # replica recovers; the next due probe closes the breaker
        r0.behavior = "ok"
        clk.t = 2.5
        rt.submit([1]).result(timeout=1)
        assert r0.calls == 5
        assert rt.breaker_states()["r0"] == "closed"
        assert reg.get("serve_fleet_breaker_state").value(
            replica="r0") == 0

    def test_wire_error_counts_toward_breaker(self):
        clk = _FakeClock()
        r0, r1 = _FakeReplica("r0", "wire"), _FakeReplica("r1")
        rt = FleetRouter([r0, r1], registry=_reg(),
                         breaker_threshold=2, clock=clk)
        for _ in range(5):
            rt.submit([1]).result(timeout=1)
        assert r0.calls == 2
        assert rt.breaker_states()["r0"] == "open"

    def test_unreadable_depth_sorts_last_not_first(self):
        """The _depth regression: a replica whose queue can't be read
        must sort LAST — returning 0 made the sickest replica the most
        attractive target."""
        assert FleetRouter._depth(_FakeReplica("x", depth="raise")) \
            == float("inf")
        bad = _FakeReplica("bad", depth="raise")
        ok = _FakeReplica("ok", depth=7)     # busy but readable
        rt = FleetRouter([bad, ok], registry=_reg())
        rt.submit([1]).result(timeout=1)
        assert ok.calls == 1 and bad.calls == 0

    def test_crashed_engine_failover_regression(self, lm, tmp_path):
        """fleet.py:191 regression, with REAL engines: one crashed
        replica raises ReplicaCrashed (a ServingError subclass the old
        failover clause let through) — routing must survive while the
        healthy replica has capacity."""
        e0 = _engine(lm, telemetry_dir=str(tmp_path))
        e1 = _engine(lm)
        e0._crash(RuntimeError("boom"))
        with pytest.raises(ReplicaCrashed, match="crashed"):
            e0.submit([1, 2], max_new_tokens=2)
        rt = FleetRouter(
            [ServingReplica(e0, name="r0", registry=_reg()),
             ServingReplica(e1, name="r1", registry=_reg())],
            registry=_reg())
        futs = [rt.submit([1, 2, 3], max_new_tokens=3,
                          temperature=0.0) for _ in range(4)]
        e1.run_until_idle()
        for f in futs:
            assert len(f.result(timeout=10)["tokens"]) == 3
            assert f.deliveries == 1

    def test_injected_submit_wire_fault_fails_over(self, lm):
        """resilience/faults.py fleet fault point: the submit RPC dies
        on the wire (ConnectionError) before the engine sees it; the
        router classifies it as a replica failure and fails over."""
        plan = FaultPlan().fail_submit(1, times=3)
        e0 = _engine(lm, faults=plan)
        e1 = _engine(lm)
        rt = FleetRouter(
            [ServingReplica(e0, name="r0", registry=_reg()),
             ServingReplica(e1, name="r1", registry=_reg())],
            registry=_reg(), breaker_threshold=5)
        f = rt.submit([1, 2], max_new_tokens=2, temperature=0.0)
        e1.run_until_idle()
        assert len(f.result(timeout=10)["tokens"]) == 2
        assert [k for _s, k in plan.fired] == ["submit_wire"]
        assert e0._submit_seq == 1 and e1._submit_seq == 1


class TestExactlyOnceRedispatch:
    def test_redispatch_token_identity_vs_uninterrupted_run(
            self, lm, tmp_path):
        """THE acceptance invariant: a crash-after-admit strands the
        request on replica 0; the survivor's re-run produces tokens
        bitwise identical to an uninterrupted greedy run (same
        weights, deterministic decode) — and delivery happens exactly
        once."""
        plan = FaultPlan()
        e0 = _engine(lm, faults=plan, telemetry_dir=str(tmp_path))
        e1 = _engine(lm)
        prompt = [1, 2, 3, 4]
        ref = e1.submit(prompt, max_new_tokens=6, temperature=0.0)
        e1.run_until_idle()
        ref_tokens = ref.result(timeout=10)["tokens"]
        assert len(ref_tokens) == 6
        e1.start()
        reg = _reg()
        rt = FleetRouter(
            [ServingReplica(e0, name="r0", registry=_reg()),
             ServingReplica(e1, name="r1", registry=_reg())],
            registry=reg)
        plan.crash_after_admit(next(Request._ids) + 1)
        f = rt.submit(prompt, max_new_tokens=6, temperature=0.0,
                      timeout=30)
        res = f.result(timeout=30)
        assert res["tokens"] == ref_tokens
        assert f.deliveries == 1
        assert f.attempts == 2 and f.redispatches == 1
        assert reg.get("serve_fleet_redispatch_total").total() == 1
        # the dead replica counted its stranded request
        assert e0._reg.get(
            "serve_stranded_requests_total").total() == 1
        e1.stop()

    def test_budget_exhausted_fails_typed_504_exactly_once(self):
        """Retries never reset the clock: the re-dispatched attempt
        carries the REMAINING budget, and when it runs out the request
        fails RequestTimeout (the gateway's 504) exactly once — not a
        silent hang, not a fresh 120s."""
        b0 = _FakeReplica("b0", "blackhole")
        b1 = _FakeReplica("b1", "blackhole")
        rt = FleetRouter([b0, b1], registry=_reg(),
                         per_try_timeout=0.08)
        f = rt.submit([1], timeout=0.12)
        t0 = time.monotonic()
        with pytest.raises(RequestTimeout, match="budget exhausted"):
            f.result()
        took = time.monotonic() - t0
        assert took < 1.0                    # bounded by the budget
        assert f.done() and f.deliveries == 1
        # the second attempt inherited the REMAINDER, not a reset clock
        assert b1.calls == 1
        assert 0.0 < b1.last_kwargs["timeout"] < 0.12 - 0.08 + 0.02
        # exactly once: a second result() re-raises, no new delivery
        with pytest.raises(RequestTimeout):
            f.result()
        assert f.deliveries == 1

    def test_slow_replica_second_attempt_under_remainder(self, lm):
        """Acceptance: an injected slow-replica fault fires the
        per-try timeout; the survivor's attempt runs under the
        ORIGINAL deadline's remainder and completes well inside it."""
        plan = FaultPlan().slow_replica(0, seconds=4.0, times=1)
        e0 = _engine(lm, faults=plan)
        e1 = _engine(lm)

        class _Recorder(ServingReplica):
            def submit(self, *a, **kw):
                self.seen = dict(kw)
                return super().submit(*a, **kw)

        r1 = _Recorder(e1, name="r1", registry=_reg())
        e0.start()
        e1.start()
        # warm the survivor so the re-dispatched attempt measures
        # decode speed, not first-request compile time
        e1.submit([1], max_new_tokens=1,
                  temperature=0.0).result(timeout=60)
        rt = FleetRouter(
            [ServingReplica(e0, name="r0", registry=_reg()), r1],
            registry=_reg(), per_try_timeout=2.0)
        t0 = time.monotonic()
        f = rt.submit([1, 2, 3], max_new_tokens=4, temperature=0.0,
                      timeout=30.0)
        res = f.result(timeout=30)
        took = time.monotonic() - t0
        assert len(res["tokens"]) == 4 and f.redispatches == 1
        assert took < 30.0
        assert 0.0 < r1.seen["timeout"] < 30.0 - 2.0 + 0.1
        e0.stop()
        e1.stop()

    def test_double_delivery_raises_on_late_original(self):
        """The once-guard, fleet-level: after the future fulfilled, a
        second fulfillment attempt raises (mirrors ServeFuture's
        tested guard) — a late original can never overwrite the
        survivor's response."""
        r = _FakeReplica("r")
        rt = FleetRouter([r], registry=_reg())
        f = rt.submit([1])
        assert f.result(timeout=1)["tokens"] == [1, 2, 3]
        with pytest.raises(RuntimeError, match="double delivery"):
            f._fulfill(result={"tokens": [9]})
        assert f.result(timeout=1)["tokens"] == [1, 2, 3]

    def test_delivered_backpressure_is_redispatched(self):
        """An error DELIVERED through the future that means 'never
        served' (hard-stopped engine → EngineDraining) re-dispatches
        instead of failing the caller."""
        h0 = _FakeReplica("h0", "blackhole")
        r1 = _FakeReplica("r1")
        rt = FleetRouter([h0, r1], registry=_reg())
        f = rt.submit([1], timeout=10)
        h0.futures[0].set_error(EngineDraining("engine stopped"))
        assert f.result(timeout=5)["tokens"] == [1, 2, 3]
        assert f.redispatches == 1 and f.deliveries == 1


class TestShedPolicy:
    def test_sustained_backpressure_sheds_typed_and_fast(self):
        clk = _FakeClock()
        shed = ShedPolicy(window_s=30.0, threshold=3, retry_after=2.5)
        f0 = _FakeReplica("f0", "full")
        f1 = _FakeReplica("f1", "full")
        reg = _reg()
        rt = FleetRouter([f0, f1], registry=reg, shed_policy=shed,
                         clock=clk)
        # below the threshold: the all-refused error stays plain
        with pytest.raises(ServingError) as ei:
            rt.submit([1])
        assert not isinstance(ei.value, RequestShed)
        # this pass crosses the threshold → typed shed w/ retry_after
        with pytest.raises(RequestShed) as ei:
            rt.submit([1])
        assert ei.value.retry_after == 2.5
        # sustained: fast-fail at the door — no replica is touched
        calls = f0.calls + f1.calls
        with pytest.raises(RequestShed):
            rt.submit([1])
        assert f0.calls + f1.calls == calls
        assert reg.get("serve_fleet_shed_total").total() == 2

    def test_brownout_steps_down_before_refusing(self):
        clk = _FakeClock()
        shed = ShedPolicy(window_s=30.0, threshold=1, retry_after=1.0,
                          brownout=brownout_shrink_generation)
        g = _FakeReplica("g")
        reg = _reg()
        rt = FleetRouter([g], registry=reg, shed_policy=shed,
                         clock=clk)
        shed.record_backpressure(clk())
        f = rt.submit([1], max_new_tokens=8)
        assert f.result(timeout=1)["tokens"] == [1, 2, 3]
        assert g.last_kwargs["max_new_tokens"] == 4   # halved
        assert reg.get("serve_fleet_brownout_total").total() == 1
        # nothing left to shrink → the hook declines → typed shed
        with pytest.raises(RequestShed):
            rt.submit([1], max_new_tokens=1)

    def test_engine_speculation_throttle_is_a_brownout_knob(self, lm):
        eng = lm.compile_serving(slots=2, max_len=32, prefill_len=8,
                                 kv_layout="paged", speculative_k=4,
                                 registry=_reg())
        assert eng._spec_throttled is False
        eng.throttle_speculation(True)
        fut = eng.submit([1, 2, 3], max_new_tokens=5, temperature=0.0)
        eng.run_until_idle()
        assert len(fut.result(timeout=10)["tokens"]) == 5
        # throttled: no drafts proposed, one token per tick
        assert eng._reg.get("speculative_proposed_total").total() == 0
        eng.throttle_speculation(False)


class TestCrashSurfacing:
    def test_crash_strands_admitted_requests_typed_and_counted(
            self, lm, tmp_path):
        eng = _engine(lm, telemetry_dir=str(tmp_path))
        f1 = eng.submit([1, 2], max_new_tokens=2)
        f2 = eng.submit([3, 4], max_new_tokens=2)
        eng._crash(RuntimeError("boom"))
        for f in (f1, f2):
            with pytest.raises(ReplicaCrashed,
                               match="serve loop crashed"):
                f.result(timeout=1)
        assert eng._reg.get(
            "serve_stranded_requests_total").total() == 2
        with pytest.raises(ReplicaCrashed):
            eng.submit([5], max_new_tokens=1)


class TestGatewayContracts:
    @staticmethod
    def _raw_post(port, head):
        s = socket.create_connection(("127.0.0.1", port), timeout=5)
        s.sendall(head.encode())
        data = s.recv(4096).decode()
        s.close()
        return data

    def test_body_cap_413_matrix_and_single_deadline(self, lm):
        eng = _engine(lm)
        eng.start()
        srv, port = serve_gateway(eng, max_body_bytes=256)
        try:
            # missing Content-Length: refused before any read
            resp = self._raw_post(
                port, "POST /v1/generate HTTP/1.1\r\n"
                      "Host: t\r\nConnection: close\r\n\r\n")
            assert resp.startswith("HTTP/1.1 413")
            # garbage Content-Length
            resp = self._raw_post(
                port, "POST /v1/generate HTTP/1.1\r\nHost: t\r\n"
                      "Content-Length: banana\r\n"
                      "Connection: close\r\n\r\n")
            assert resp.startswith("HTTP/1.1 413")
            # declared size over the cap: refused by the DECLARATION
            c = http.client.HTTPConnection("127.0.0.1", port,
                                           timeout=10)
            c.request("POST", "/v1/generate",
                      json.dumps({"prompt": [1] * 500}))
            r = c.getresponse()
            doc = json.loads(r.read())
            c.close()
            assert r.status == 413 and "exceeds" in doc["error"]
            # one deadline: an already-due request 504s (typed), and
            # the engine-side Request carried the SAME clock
            c = http.client.HTTPConnection("127.0.0.1", port,
                                           timeout=10)
            c.request("POST", "/v1/generate",
                      json.dumps({"prompt": [1, 2],
                                  "max_new_tokens": 4,
                                  "timeout": 0.0}))
            r = c.getresponse()
            r.read()
            c.close()
            assert r.status == 504
            # a healthy request still round-trips
            c = http.client.HTTPConnection("127.0.0.1", port,
                                           timeout=30)
            c.request("POST", "/v1/generate",
                      json.dumps({"prompt": [1, 2],
                                  "max_new_tokens": 3,
                                  "temperature": 0.0}))
            r = c.getresponse()
            doc = json.loads(r.read())
            c.close()
            assert r.status == 200 and len(doc["tokens"]) == 3
        finally:
            srv.shutdown()
            srv.server_close()
            eng.stop()

    def test_fleet_front_gateway_health_shed_and_retry_after(self):
        shed = ShedPolicy(window_s=30.0, threshold=1, retry_after=2.0)
        rep = _FakeReplica("r0")
        rt = FleetRouter([rep], registry=_reg(), shed_policy=shed)
        srv, port = serve_gateway(rt)
        try:
            c = http.client.HTTPConnection("127.0.0.1", port,
                                           timeout=10)
            c.request("GET", "/healthz")
            r = c.getresponse()
            doc = json.loads(r.read())
            c.close()
            assert r.status == 200
            assert doc["breakers"] == {"r0": "closed"}
            assert doc["replicas"][0]["status"] == "serving"
            # routed generate round-trips through the router
            c = http.client.HTTPConnection("127.0.0.1", port,
                                           timeout=10)
            c.request("POST", "/v1/generate",
                      json.dumps({"prompt": [1, 2],
                                  "max_new_tokens": 2}))
            r = c.getresponse()
            doc = json.loads(r.read())
            c.close()
            assert r.status == 200 and doc["tokens"] == [1, 2, 3]
            # sustained shed → 503 + the Retry-After contract
            shed.record_backpressure(time.monotonic())
            c = http.client.HTTPConnection("127.0.0.1", port,
                                           timeout=10)
            c.request("POST", "/v1/generate",
                      json.dumps({"prompt": [1, 2]}))
            r = c.getresponse()
            doc = json.loads(r.read())
            retry_after = r.getheader("Retry-After")
            c.close()
            assert r.status == 503
            assert retry_after == "2"
            assert doc["retryable"] is True
            assert doc["retry_after"] == 2.0
        finally:
            srv.shutdown()
            srv.server_close()


class TestObservability:
    def test_heartbeat_summary_carries_fleet_block(self):
        clk = _FakeClock()
        reg = _reg()
        r0, r1 = _FakeReplica("r0", "crashed"), _FakeReplica("r1")
        rt = FleetRouter([r0, r1], registry=reg, breaker_threshold=1,
                         clock=clk)
        rt.submit([1]).result(timeout=1)
        hs = obs_metrics.heartbeat_summary(reg)
        fl = hs["serving_fleet"]
        assert fl["submitted"] == 1
        assert fl["failovers"] == 1
        assert fl["breaker_opens"] == 1
        assert fl["breakers_open"] == 1
        assert fl["sheds"] == 0

    def test_block_pool_exhausted_is_backpressure_to_the_router(self):
        """BlockPoolExhausted at submit is failover + shed evidence,
        never a breaker failure (the replica is healthy, the request
        just can't fit it)."""
        class _PoolFull(_FakeReplica):
            def submit(self, *a, **kw):
                self.calls += 1
                raise BlockPoolExhausted("pool too small")

        p = _PoolFull("p")
        ok = _FakeReplica("ok")
        rt = FleetRouter([p, ok], registry=_reg(),
                         breaker_threshold=1)
        for _ in range(3):
            assert rt.submit([1]).result(timeout=1)["tokens"] \
                == [1, 2, 3]
        assert p.calls == 3              # still tried: breaker closed
        assert rt.breaker_states()["p"] == "closed"
