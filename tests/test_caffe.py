"""Caffe import: prototxt parse, layer conversion, caffemodel weight
loading, numeric parity vs a numpy oracle (capability the reference
declares via its vendored src/proto/caffe.proto)."""

import numpy as np
import pytest

from singa_tpu import caffe, device
from singa_tpu.caffe_proto import caffe_pb2
from singa_tpu.tensor import Tensor

DEV = device.create_cpu_device()
RNG = np.random.RandomState(3)


LENET_PROTOTXT = """
name: "MiniLeNet"
input: "data"
input_shape { dim: 1 dim: 1 dim: 12 dim: 12 }
layer {
  name: "conv1"
  type: "Convolution"
  bottom: "data"
  top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 stride: 1 pad: 1 }
}
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer {
  name: "pool1"
  type: "Pooling"
  bottom: "conv1"
  top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "ip1"
  type: "InnerProduct"
  bottom: "pool1"
  top: "ip1"
  inner_product_param { num_output: 5 }
}
layer { name: "prob" type: "Softmax" bottom: "ip1" top: "prob" }
"""


def make_caffemodel():
    """Binary NetParameter with trained blobs for MiniLeNet."""
    w = caffe_pb2.NetParameter()
    conv = w.layer.add()
    conv.name, conv.type = "conv1", "Convolution"
    Wc = RNG.randn(4, 1, 3, 3).astype(np.float32) * 0.5
    bc = RNG.randn(4).astype(np.float32) * 0.1
    for arr in (Wc, bc):
        b = conv.blobs.add()
        b.shape.dim.extend(arr.shape)
        b.data.extend(arr.ravel().tolist())
    ip = w.layer.add()
    ip.name, ip.type = "ip1", "InnerProduct"
    Wi = RNG.randn(5, 4 * 6 * 6).astype(np.float32) * 0.1
    bi = RNG.randn(5).astype(np.float32) * 0.1
    for arr in (Wi, bi):
        b = ip.blobs.add()
        b.shape.dim.extend(arr.shape)
        b.data.extend(arr.ravel().tolist())
    return w.SerializeToString(), (Wc, bc, Wi, bi)


def manual_forward(x, Wc, bc, Wi, bi):
    n, _, h, w = x.shape
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    conv = np.zeros((n, 4, h, w), np.float32)
    for o in range(4):
        for i in range(1):
            for dy in range(3):
                for dx in range(3):
                    conv[:, o] += Wc[o, i, dy, dx] * \
                        xp[:, i, dy:dy + h, dx:dx + w]
        conv[:, o] += bc[o]
    relu = np.maximum(conv, 0)
    pooled = relu.reshape(n, 4, h // 2, 2, w // 2, 2).max(5).max(3)
    flat = pooled.reshape(n, -1)
    logits = flat @ Wi.T + bi
    e = np.exp(logits - logits.max(1, keepdims=True))
    return e / e.sum(1, keepdims=True)


class TestCaffeImport:
    def test_prototxt_parse_and_forward_shapes(self, tmp_path):
        p = tmp_path / "net.prototxt"
        p.write_text(LENET_PROTOTXT)
        net = caffe.load(str(p))
        x = Tensor(data=RNG.randn(2, 1, 12, 12).astype(np.float32),
                   device=DEV, requires_grad=False)
        out = net.forward(x)
        assert out.shape == (2, 5)
        probs = np.asarray(out.data)
        np.testing.assert_allclose(probs.sum(1), 1.0, rtol=1e-5)

    def test_caffemodel_weights_numeric_parity(self, tmp_path):
        p = tmp_path / "net.prototxt"
        p.write_text(LENET_PROTOTXT)
        raw, (Wc, bc, Wi, bi) = make_caffemodel()
        m = tmp_path / "net.caffemodel"
        m.write_bytes(raw)
        net = caffe.load(str(p), str(m))
        x = RNG.randn(2, 1, 12, 12).astype(np.float32)
        out = net.forward(Tensor(data=x, device=DEV, requires_grad=False))
        want = manual_forward(x, Wc, bc, Wi, bi)
        np.testing.assert_allclose(np.asarray(out.data), want,
                                   rtol=1e-4, atol=1e-5)

    def test_states_include_all_converted_params(self, tmp_path):
        """InnerProduct (and every converted layer) must appear in
        get_states so checkpointing an imported net is lossless."""
        p = tmp_path / "net.prototxt"
        p.write_text(LENET_PROTOTXT)
        raw, (Wc, bc, Wi, bi) = make_caffemodel()
        m = tmp_path / "net.caffemodel"
        m.write_bytes(raw)
        net = caffe.load(str(p), str(m))
        states = net.get_states()
        ip_w = [k for k in states if "ip1" in k and k.endswith(".W")]
        assert ip_w, list(states)
        np.testing.assert_allclose(np.asarray(states[ip_w[0]].data),
                                   Wi.T, rtol=1e-6)
        conv_w = [k for k in states if "conv1" in k and k.endswith(".W")]
        assert conv_w, list(states)

    def test_ceil_pooling_shape(self):
        """caffe pools with CEIL output sizing: 3x3 stride-2 on 6x6 is
        3x3 (floor would give 2x2), last window clipped at the border."""
        from google.protobuf import text_format
        net_def = text_format.Parse("""
        layer { name: "p" type: "Pooling" bottom: "d" top: "p"
                pooling_param { pool: MAX kernel_size: 3 stride: 2 } }
        """, caffe_pb2.NetParameter())
        net = caffe.CaffeConverter(net_def).create_net()
        x = RNG.randn(1, 2, 6, 6).astype(np.float32)
        out = net.forward(Tensor(data=x, device=DEV, requires_grad=False))
        assert out.shape == (1, 2, 3, 3), out.shape
        # values: max over each (border-clipped) 3x3 window on the grid
        want = np.full((1, 2, 3, 3), -np.inf, np.float32)
        for i in range(3):
            for j in range(3):
                want[:, :, i, j] = x[:, :, 2 * i:2 * i + 3,
                                     2 * j:2 * j + 3].max((2, 3))
        np.testing.assert_allclose(np.asarray(out.data), want, rtol=1e-6)

    def test_batchnorm_eps_honored(self):
        from google.protobuf import text_format
        net_def = text_format.Parse("""
        layer { name: "bn" type: "BatchNorm" bottom: "d" top: "b"
                batch_norm_param { eps: 0.1 use_global_stats: true } }
        """, caffe_pb2.NetParameter())
        net = caffe.CaffeConverter(net_def).create_net()
        x = RNG.randn(2, 3, 4, 4).astype(np.float32)
        out = np.asarray(net.forward(
            Tensor(data=x, device=DEV, requires_grad=False)).data)
        # fresh stats: mean 0, var 1 -> y = x / sqrt(1 + 0.1)
        np.testing.assert_allclose(out, x / np.sqrt(1.1), rtol=1e-4,
                                   atol=1e-5)

    def test_train_with_trailing_softmax(self, tmp_path):
        """Deploy prototxts end in Softmax; training must use the logits
        (no double softmax) while forward still returns probabilities."""
        from singa_tpu import opt

        p = tmp_path / "net.prototxt"
        p.write_text(LENET_PROTOTXT)
        net = caffe.load(str(p))
        net.set_optimizer(opt.SGD(lr=0.05, momentum=0.9))
        x = Tensor(data=RNG.randn(8, 1, 12, 12).astype(np.float32),
                   device=DEV, requires_grad=False)
        y = Tensor(data=np.eye(5)[RNG.randint(0, 5, 8)].astype(np.float32),
                   device=DEV, requires_grad=False)
        net.compile([x], is_train=True, use_graph=True)
        losses = []
        for _ in range(8):
            out, loss = net(x, y)
            losses.append(float(np.asarray(loss.data)))
        assert losses[-1] < losses[0], losses
        np.testing.assert_allclose(np.asarray(out.data).sum(1), 1.0,
                                   rtol=1e-4)

    def test_imported_net_trains(self, tmp_path):
        from singa_tpu import opt

        p = tmp_path / "net.prototxt"
        # training net: no trailing Softmax (train_one_batch adds the loss)
        p.write_text(LENET_PROTOTXT.replace(
            'layer { name: "prob" type: "Softmax" bottom: "ip1" '
            'top: "prob" }', ""))
        net = caffe.load(str(p))
        net.set_optimizer(opt.SGD(lr=0.05, momentum=0.9))
        x = Tensor(data=RNG.randn(8, 1, 12, 12).astype(np.float32),
                   device=DEV, requires_grad=False)
        y = Tensor(data=np.eye(5)[RNG.randint(0, 5, 8)].astype(np.float32),
                   device=DEV, requires_grad=False)
        net.compile([x], is_train=True, use_graph=True)
        losses = [float(np.asarray(net(x, y)[1].data)) for _ in range(8)]
        assert losses[-1] < losses[0], losses

    def test_batchnorm_scale_pair(self):
        npz = caffe_pb2.NetParameter()
        txt = """
        name: "bn"
        layer { name: "bn1" type: "BatchNorm" bottom: "d" top: "b"
                batch_norm_param { eps: 1e-5 } }
        layer { name: "sc1" type: "Scale" bottom: "b" top: "s"
                scale_param { bias_term: true } }
        """
        from google.protobuf import text_format
        net_def = text_format.Parse(txt, npz)
        w = caffe_pb2.NetParameter()
        mean = np.asarray([1.0, -2.0], np.float32)
        var = np.asarray([4.0, 9.0], np.float32)
        bn = w.layer.add()
        bn.name, bn.type = "bn1", "BatchNorm"
        for arr in (mean * 2, var * 2, np.asarray([2.0], np.float32)):
            b = bn.blobs.add()
            b.shape.dim.extend(arr.shape)
            b.data.extend(np.ravel(arr).tolist())
        sc = w.layer.add()
        sc.name, sc.type = "sc1", "Scale"
        gamma = np.asarray([1.5, 0.5], np.float32)
        beta = np.asarray([0.1, -0.1], np.float32)
        for arr in (gamma, beta):
            b = sc.blobs.add()
            b.shape.dim.extend(arr.shape)
            b.data.extend(arr.tolist())

        cv = caffe.CaffeConverter(net_def, w.SerializeToString())
        net = cv.create_net()
        x = RNG.randn(3, 2, 4, 4).astype(np.float32)
        tx = Tensor(data=x, device=DEV, requires_grad=False)
        cv.load_weights(net, tx)
        net.eval()
        out = np.asarray(net.forward(tx).data)
        want = ((x - mean[None, :, None, None])
                / np.sqrt(var[None, :, None, None] + 1e-5)
                * gamma[None, :, None, None] + beta[None, :, None, None])
        np.testing.assert_allclose(out, want, rtol=1e-3, atol=1e-3)

    def test_lrn_numeric(self):
        from singa_tpu import autograd
        x = RNG.randn(2, 6, 3, 3).astype(np.float32)
        size, alpha, beta, k = 5, 1e-2, 0.75, 1.0
        out = autograd.lrn(Tensor(data=x, device=DEV, requires_grad=True),
                           size, alpha, beta, k)
        # naive numpy oracle
        want = np.empty_like(x)
        half = size // 2
        for c in range(6):
            lo, hi = max(0, c - half), min(6, c + size - half)
            s = (x[:, lo:hi] ** 2).sum(1)
            want[:, c] = x[:, c] / (k + alpha / size * s) ** beta
        np.testing.assert_allclose(np.asarray(out.data), want,
                                   rtol=1e-4, atol=1e-5)

    def test_caffe_net_with_lrn_exports_to_onnx(self, tmp_path):
        """caffe import -> ONNX export round-trip (LRN maps to the native
        ONNX LRN op)."""
        from singa_tpu import sonnx

        txt = LENET_PROTOTXT.replace(
            'layer { name: "relu1" type: "ReLU" bottom: "conv1" '
            'top: "conv1" }',
            'layer { name: "relu1" type: "ReLU" bottom: "conv1" '
            'top: "conv1" }\n'
            'layer { name: "norm1" type: "LRN" bottom: "conv1" '
            'top: "conv1" lrn_param { local_size: 3 alpha: 0.01 } }')
        p = tmp_path / "net.prototxt"
        p.write_text(txt)
        net = caffe.load(str(p))
        x = Tensor(data=RNG.randn(2, 1, 12, 12).astype(np.float32),
                   device=DEV, requires_grad=True)
        net.forward(x)
        mp = sonnx.to_onnx(net, [x], "caffe_lrn")
        assert "LRN" in [n.op_type for n in mp.graph.node]
        rep = sonnx.prepare(mp, device="CPU")
        got = rep.run([x])[0]
        np.testing.assert_allclose(np.asarray(got.data),
                                   np.asarray(net.forward(x).data),
                                   rtol=1e-4, atol=1e-5)

    def test_unknown_layer_raises(self):
        from google.protobuf import text_format
        net = text_format.Parse(
            'layer { name: "x" type: "Embed" }', caffe_pb2.NetParameter())
        with pytest.raises(NotImplementedError):
            caffe.CaffeConverter(net).create_net()
