"""Layer zoo: shape inference, forward oracles, params/states plumbing
(reference test/python/test_layer.py + test_operation conv/bn/pool cases)."""

import numpy as np
import jax
import jax.numpy as jnp

from singa_tpu import autograd, device, layer
from singa_tpu.tensor import Tensor


DEV = device.create_cpu_device()


def t(arr, rg=False):
    return Tensor(data=np.asarray(arr, np.float32), device=DEV,
                  requires_grad=rg, stores_grad=rg)


class TestLinear:
    def test_shapes_and_params(self):
        x = t(np.random.randn(3, 7))
        fc = layer.Linear(4)
        y = fc(x)
        assert y.shape == (3, 4)
        params = fc.get_params()
        assert set(params) == {"Linear.W", "Linear.b"}
        assert params["Linear.W"].shape == (7, 4)

    def test_forward_oracle(self):
        x = t(np.random.randn(3, 7))
        fc = layer.Linear(4)
        y = fc(x)
        W = np.asarray(fc.W.data)
        b = np.asarray(fc.b.data)
        np.testing.assert_allclose(np.asarray(y.data),
                                   np.asarray(x.data) @ W + b, rtol=1e-5)

    def test_legacy_two_arg_form(self):
        fc = layer.Linear(7, 4)
        y = fc(t(np.random.randn(3, 7)))
        assert y.shape == (3, 4)

    def test_set_get_params_roundtrip(self):
        fc = layer.Linear(4)
        fc(t(np.random.randn(3, 7)))
        p = fc.get_params()
        newW = t(np.ones((7, 4)))
        fc.set_params({"Linear.W": newW})
        np.testing.assert_array_equal(np.asarray(fc.W.data), 1.0)


class TestConv2d:
    def test_identity_kernel(self):
        x = np.random.randn(2, 3, 5, 5).astype(np.float32)
        conv = layer.Conv2d(3, 1, bias=False)
        y = conv(t(x))
        # set 1x1 identity weights: out c = in c
        W = np.zeros((3, 3, 1, 1), np.float32)
        for c in range(3):
            W[c, c, 0, 0] = 1.0
        conv.W.copy_from_numpy(W)
        y = conv(t(x))
        np.testing.assert_allclose(np.asarray(y.data), x, rtol=1e-5)

    def test_vs_lax_oracle(self):
        x = np.random.randn(2, 3, 8, 8).astype(np.float32)
        conv = layer.Conv2d(5, 3, stride=2, padding=1)
        y = conv(t(x))
        W = np.asarray(conv.W.data)
        b = np.asarray(conv.b.data)
        ref = jax.lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(W), (2, 2), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        ref = ref + jnp.asarray(b)[None, :, None, None]
        np.testing.assert_allclose(np.asarray(y.data), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)
        assert y.shape == (2, 5, 4, 4)

    def test_grouped(self):
        x = np.random.randn(2, 4, 6, 6).astype(np.float32)
        conv = layer.Conv2d(4, 3, padding=1, group=4, bias=False)
        y = conv(t(x))
        assert y.shape == (2, 4, 6, 6)

    def test_separable(self):
        x = np.random.randn(2, 4, 6, 6).astype(np.float32)
        sep = layer.SeparableConv2d(8, 3, padding=1)
        y = sep(t(x))
        assert y.shape == (2, 8, 6, 6)
        names = set(sep.get_params())
        assert any("depthwise" in n for n in names)
        assert any("pointwise" in n for n in names)


class TestBatchNorm:
    def test_train_normalizes(self):
        autograd.training = True
        try:
            x = np.random.RandomState(0).randn(8, 3, 4, 4) * 3 + 5
            bn = layer.BatchNorm2d()
            y = bn(t(x.astype(np.float32), rg=True))
            vals = np.asarray(y.data)
            np.testing.assert_allclose(vals.mean(axis=(0, 2, 3)), 0.0,
                                       atol=1e-4)
            np.testing.assert_allclose(vals.std(axis=(0, 2, 3)), 1.0,
                                       atol=1e-2)
        finally:
            autograd.training = False

    def test_running_stats_update_and_eval(self):
        autograd.training = True
        try:
            rs = np.random.RandomState(1)
            bn = layer.BatchNorm2d(momentum=0.0)  # running <- batch stats
            x = rs.randn(16, 2, 3, 3).astype(np.float32) * 2 + 1
            bn(t(x, rg=True))
            rm = np.asarray(bn.running_mean.data)
            np.testing.assert_allclose(rm, x.mean(axis=(0, 2, 3)), atol=1e-4)
        finally:
            autograd.training = False
        # eval mode uses running stats
        y = bn(t(x))
        expect = (x - rm[None, :, None, None]) / np.sqrt(
            np.asarray(bn.running_var.data)[None, :, None, None] + bn.eps)
        np.testing.assert_allclose(np.asarray(y.data), expect, atol=1e-3)

    def test_bf16_moments_accumulate_in_f32(self):
        """A bf16 sum over N*H*W elements loses most of its mantissa;
        _global_moments upcasts before reducing, so bf16 BN's running
        stats must land within bf16 INPUT precision of the f32 run
        (not bf16 ACCUMULATION error, which is ~100x worse here)."""
        import jax.numpy as jnp
        autograd.training = True
        try:
            rs = np.random.RandomState(3)
            x = (rs.randn(64, 2, 16, 16) * 2 + 3).astype(np.float32)
            bn32 = layer.BatchNorm2d(momentum=0.0)
            bn32(t(x, rg=True))
            bn16 = layer.BatchNorm2d(momentum=0.0)
            bn16(Tensor(data=jnp.asarray(x, jnp.bfloat16),
                        requires_grad=True, stores_grad=True))
            np.testing.assert_allclose(
                np.asarray(bn16.running_mean.data, np.float32),
                np.asarray(bn32.running_mean.data), rtol=2e-2, atol=2e-2)
            np.testing.assert_allclose(
                np.asarray(bn16.running_var.data, np.float32),
                np.asarray(bn32.running_var.data), rtol=2e-2)
            assert bn16.running_mean.data.dtype == jnp.float32
        finally:
            autograd.training = False

    def test_states_include_running(self):
        bn = layer.BatchNorm2d()
        bn(t(np.random.randn(2, 3, 4, 4).astype(np.float32)))
        st = bn.get_states()
        assert "BatchNorm2d.running_mean" in st
        assert "BatchNorm2d.running_var" in st
        assert set(bn.get_params()) == {"BatchNorm2d.scale",
                                        "BatchNorm2d.bias"}


class TestPooling:
    def test_maxpool(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        y = layer.MaxPool2d(2, 2)(t(x))
        np.testing.assert_array_equal(
            np.asarray(y.data).reshape(2, 2), [[5, 7], [13, 15]])

    def test_avgpool(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        y = layer.AvgPool2d(2, 2)(t(x))
        np.testing.assert_allclose(
            np.asarray(y.data).reshape(2, 2), [[2.5, 4.5], [10.5, 12.5]])

    def test_pool1d(self):
        x = np.arange(8, dtype=np.float32).reshape(1, 1, 1, 8)
        y = layer.MaxPool1d(2, 2)(t(x))
        np.testing.assert_array_equal(np.asarray(y.data).ravel(),
                                      [1, 3, 5, 7])

    def test_padded_max(self):
        x = np.ones((1, 1, 3, 3), np.float32)
        y = layer.MaxPool2d(2, 2, padding=1)(t(x))
        assert y.shape == (1, 1, 2, 2)


class TestRNNLayers:
    def test_vanilla_rnn(self):
        autograd.training = True
        try:
            rnn = layer.RNN(4, 6)
            xs = [t(np.random.randn(2, 4), rg=True) for _ in range(3)]
            h0 = t(np.zeros((2, 6)))
            out, h = rnn(xs, h0)
            assert len(out) == 3 and h.shape == (2, 6)
        finally:
            autograd.training = False

    def test_lstm(self):
        autograd.training = True
        try:
            lstm = layer.LSTM(4, 6)
            xs = [t(np.random.randn(2, 4), rg=True) for _ in range(3)]
            h0, c0 = t(np.zeros((2, 6))), t(np.zeros((2, 6)))
            out, (h, c) = lstm(xs, (h0, c0))
            assert len(out) == 3 and h.shape == (2, 6) and c.shape == (2, 6)
        finally:
            autograd.training = False

    def test_fused_lstm_shapes(self):
        autograd.training = True
        try:
            rnn = layer.CudnnRNN(8, rnn_mode="lstm")
            x = t(np.random.randn(5, 2, 3), rg=True)  # (seq, batch, feat)
            y, hy, cy = rnn(x)
            assert y.shape == (5, 2, 8)
            assert hy.shape == (1, 2, 8)
        finally:
            autograd.training = False

    def test_fused_gru_and_tanh(self):
        autograd.training = True
        try:
            for mode in ("gru", "tanh", "relu"):
                rnn = layer.CudnnRNN(4, rnn_mode=mode)
                y, hy, cy = rnn(t(np.random.randn(3, 2, 5), rg=True))
                assert y.shape == (3, 2, 4), mode
        finally:
            autograd.training = False

    def test_bidirectional(self):
        autograd.training = True
        try:
            rnn = layer.CudnnRNN(4, rnn_mode="lstm", bidirectional=True)
            y, hy, cy = rnn(t(np.random.randn(3, 2, 5), rg=True))
            assert y.shape == (3, 2, 8)
            assert hy.shape == (2, 2, 4)
        finally:
            autograd.training = False


class TestMisc:
    def test_embedding_layer(self):
        emb = layer.Embedding(10, 4)
        ids = t(np.array([[1, 2], [3, 4]], np.float32))
        y = emb(ids)
        assert y.shape == (2, 2, 4)

    def test_stateless_layers(self):
        x = t(np.random.randn(3, 4))
        assert layer.ReLU()(x).shape == (3, 4)
        assert layer.Sigmoid()(x).shape == (3, 4)
        assert layer.Tanh()(x).shape == (3, 4)
        assert layer.SoftMax()(x).shape == (3, 4)
        assert layer.Flatten()(t(np.random.randn(3, 2, 2))).shape == (3, 4)

    def test_nested_param_names(self):
        class Block(layer.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = layer.Linear(4)
                self.fc2 = layer.Linear(2)

            def forward(self, x):
                return self.fc2(self.fc1(x))

        b = Block()
        b(t(np.random.randn(3, 7)))
        names = set(b.get_params())
        assert names == {"Block.fc1.W", "Block.fc1.b",
                         "Block.fc2.W", "Block.fc2.b"}
