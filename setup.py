"""pip packaging for singa-tpu (capability parity with the reference's
setup.py wheel build, reference setup.py:140-222 — but with no SWIG/nvcc
machinery: the only native artifact is the C-ABI IO runtime, compiled with
the in-tree Makefile and shipped inside ``singa_tpu/native``).

The native build is best-effort: when no C++ toolchain is available the
wheel still works — every native entry point has a pure-python fallback
(see singa_tpu/native/__init__.py AVAILABLE).
"""

import os
import shutil
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


ROOT = os.path.dirname(os.path.abspath(__file__))


class build_py_with_native(build_py):
    """Build libsinga_native.so via the Makefile and ship it in-package."""

    def run(self):
        super().run()
        native_dir = os.path.join(ROOT, "native")
        libs = [os.path.join(native_dir, n)
            for n in ("libsinga_native.so", "libsinga_network.so")]
        try:
            subprocess.run(["make", "-C", native_dir], check=True)
        except (subprocess.SubprocessError, OSError) as e:
            self.warn(f"native build skipped ({e}); the package will use "
                      "pure-python fallbacks")
            return
        dest_dir = os.path.join(self.build_lib, "singa_tpu", "native")
        os.makedirs(dest_dir, exist_ok=True)
        for lib in libs:
            shutil.copy2(lib, dest_dir)


setup(cmdclass={"build_py": build_py_with_native})
