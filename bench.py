"""Benchmark harness: ResNet-50 synthetic-data training throughput.

The reference's headline harness (examples/cnn/benchmark.py:85-87) measures
`throughput = niters * batch * world / (end - start)` on ResNet-50 with
synthetic data. The reference publishes no numbers (BASELINE.md), so
``vs_baseline`` reports against our own first recorded TPU run when one
exists (BENCH_BASELINE env), else 1.0.

Structure: the parent process is a pure orchestrator — it probes TPU
liveness in a bounded child (a hung backend init must not eat the time
budget), runs the real benchmark in a child subprocess with a hard timeout
(two attempts — the backend can also fail transiently mid-run), and falls
back to a clearly-labeled CPU measurement as a last resort, so this script
ALWAYS exits 0 with ONE parseable JSON line:
{"metric", "value", "unit", "vs_baseline", ...extras}.
"""

import json
import math
import os
import subprocess
import sys
import time

# Every hardware observation (probe outcome, smoke sub-result, full bench)
# is appended here with a timestamp, by this script AND by the round-long
# tools/tpu_watch.py loop. With a flaky tunnel, the end-of-round run can
# then report a number banked earlier in the round instead of losing it.
OBS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tpu_observations.jsonl")

# ResNet-50 @224x224: ~4.09 GMACs forward per image; 2 flops/MAC; a training
# step (fwd + bwd wrt activations + bwd wrt weights) is ~3x forward.
RESNET50_TRAIN_FLOPS_PER_IMAGE = 4.09e9 * 2 * 3


def _lm_train_flops_per_token(d, n_layers, seq, vocab, ff_mult=4,
                              causal=True):
    """Matmul training FLOPs per token for the bench transformer.

    Per layer: qkv+o projections 4d² params, MLP 2·d·(ff_mult·d);
    plus the d·V head (the fused CE still does the full matmul, just
    chunked). Forward = 2 FLOPs per param per token; training ≈ 3×
    forward (bwd wrt activations + weights). Attention scores/values
    add 4·S·d per layer, halved when causal. The flash backward's
    score recompute is NOT counted, so the reported MFU slightly
    understates actual hardware utilisation."""
    proj = 4 * d * d + 2 * d * (ff_mult * d)
    attn_flops = 4 * seq * d * (0.5 if causal else 1.0)  # already FLOPs
    per_token_fwd = 2 * (n_layers * proj + d * vocab) + \
        n_layers * attn_flops
    return 3 * per_token_fwd


# the bench LM's shape — single source for _measure_lm and the MFU math
LM_SHAPE = {"d_model": 512, "n_layers": 6, "seq": 1024, "vocab": 32000}

# Peak dense fp32/bf16 FLOP/s per chip by TPU generation, for the MFU
# estimate. Overridable via BENCH_PEAK_TFLOPS. The table itself is
# canonical in singa_tpu.observability.metrics (the trainer's train_mfu
# gauge reads the same numbers); _peak_flops below adds the env
# overrides and the fp32-denominator labeling.


def _peak_flops(device_kind: str, dtype: str = "bf16"):
    """Peak FLOP/s for the MFU denominator, keyed by (device kind, dtype).

    TPUs publish one dense matmul peak per generation — the bf16 MXU
    figure. There is no separate public fp32 peak: at XLA's default
    precision, fp32 matmul/conv inputs execute as bf16 MXU passes with
    fp32 accumulation, so the bf16 figure IS the hardware ceiling for
    the fp32 leg too. The fp32 row is therefore labeled
    ``mfu_denominator: bf16_peak`` in the report (a fraction of chip
    peak, not of a hypothetical fp32 unit) — override with
    BENCH_PEAK_TFLOPS_FP32 to use a different denominator."""
    if dtype == "fp32":
        env32 = os.environ.get("BENCH_PEAK_TFLOPS_FP32")
        if env32:
            return float(env32) * 1e12
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    from singa_tpu.observability.metrics import device_peak_flops
    return device_peak_flops(device_kind)


# per-leg SUCCESS markers for the extra hardware probes
# (tools/tpu_probe_extra.py): the single source consumed by BOTH the
# watcher's retry logic (tools/tpu_watch.py _extras_missing) and
# _fold_extras below — a new leg added here reaches the report and the
# retry loop together. A leg with several markers (hbm_footprint) is
# complete only when ALL of them are banked.
EXTRA_SUCCESS_MARKERS = {
    "resnet_fusion_profile": ("resnet50_bf16_fusion_profile",),
    "resnet_layout_ab": ("resnet_layout_ab",),
    "lm_long_context": ("lm_bf16_s4096_remat_tokens_per_sec",),
    "lm_decode_throughput": ("lm_decode_tokens_per_sec",),
    "hbm_footprint": ("hbm_resnet50_b32_bf16", "hbm_lm_b8_s1024_bf16"),
    "lm_fusion_profile": ("lm_bf16_fusion_profile",),
    "resnet_stem_ab": ("resnet_stem_ab",),
    "fused_optim_ab": ("fused_optim_ab",),
    "grad_bucket_ab": ("grad_bucket_ab",),
    "conv_epilogue_ab": ("conv_epilogue_ab",),
    "resnet50_bf16_large_batch": ("resnet50_bf16_b128",),
    "mlp_step_time": ("mlp_mnist_b64_step_us",),
    "flash_block_sweep": ("flash_block_best",),
}


_GIT_REV_CACHE = []


def _git_rev():
    """Short commit hash stamped into measurement records, so a banked
    number is attributable to the code that produced it (None outside a
    work tree). Cached: constant for the process lifetime, and
    _record_obs calls this while holding the obs write lock."""
    if _GIT_REV_CACHE:
        return _GIT_REV_CACHE[0]
    try:
        out = subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10)
        rev = out.stdout.strip() or None
    except (OSError, subprocess.SubprocessError):
        rev = None
    _GIT_REV_CACHE.append(rev)
    return rev


def _measured_choice(env_var, choices, ab_marker, default,
                     canon=str):
    """One mechanism for "measured, not guessed" config: an env pin
    (validated — a typo'd pin warns instead of silently demoting), else
    the newest banked A/B winner from THIS round, else the default,
    each labeled with its source. Returns (value, source).

    A banked winner is trusted only while it plausibly describes the
    CURRENT code: within the same max-age window ``_fold_banked`` uses
    for the bench legs (BENCH_BANKED_MAX_AGE_H), or carrying a git
    stamp matching the current revision. Without the gate, a stale
    ``resnet_layout_ab``/``resnet_stem_ab`` record measured on older
    layout/stem code would keep steering bench config indefinitely."""
    mode = os.environ.get(env_var, "auto").lower()
    if mode in choices:
        return canon(mode), "env"
    if mode != "auto":
        print(f"bench: {env_var}={mode!r} is not "
              f"{'|'.join(choices)}|auto; using auto", file=sys.stderr)
    wanted = {canon(c) for c in choices}
    max_age = float(os.environ.get("BENCH_BANKED_MAX_AGE_H", "14")) * 3600
    rev = _git_rev()
    for o in reversed(_load_obs()):
        if (o.get("event") == "extra" and o.get("extra") == ab_marker
                and o.get("winner") in wanted):
            if _obs_age_s(o) >= max_age and \
                    not (rev and o.get("git") == rev):
                print(f"bench: ignoring stale {ab_marker} winner "
                      f"{o['winner']!r} (older than the banked max-age "
                      f"window and not stamped with the current rev)",
                      file=sys.stderr)
                continue
            return o["winner"], "measured-ab"
    return default, "default-unmeasured"


def _conv_layout():
    """Activation layout for the ResNet legs: BENCH_CONV_LAYOUT pin, or
    the banked ``resnet_layout_ab`` hardware A/B winner (the probe runs
    before the full bench in a TPU window), else NCHW."""
    return _measured_choice("BENCH_CONV_LAYOUT", ("nchw", "nhwc"),
                            "resnet_layout_ab", "NCHW",
                            canon=str.upper)


def _resnet_stem():
    """Stem for the ResNet legs, same mechanism: BENCH_RESNET_STEM pin,
    or the banked ``resnet_stem_ab`` winner (the variant is exact —
    tests pin parity — so the measured faster form is a labeled
    optimization, not a model change), else conv7."""
    return _measured_choice("BENCH_RESNET_STEM",
                            ("conv7", "space_to_depth"),
                            "resnet_stem_ab", "conv7")


def _fused_optim():
    """Fused-vs-reference optimizer update for the train legs, same
    mechanism: BENCH_FUSED_OPTIM pin, or the banked ``fused_optim_ab``
    hardware A/B winner (tools/tpu_probe_extra.py measures the b32
    bf16 ResNet step both ways; parity is test-pinned), else reference
    — the Pallas fused path (ops/fused_optim.py) is never on
    unconditionally."""
    return _measured_choice("BENCH_FUSED_OPTIM", ("fused", "reference"),
                            "fused_optim_ab", "reference")


def _grad_bucket_mb():
    """Gradient-psum bucket size (DistOpt ``bucket_mb``) for any
    multi-device leg/probe, same mechanism: BENCH_BUCKET_MB pin over a
    small sweep grid, or the banked ``grad_bucket_ab`` winner, else 0
    (per-gradient streaming psums). Returns (float_mb, source)."""
    val, src = _measured_choice("BENCH_BUCKET_MB",
                                ("0", "1", "2", "4", "8", "16"),
                                "grad_bucket_ab", "0")
    return float(val), src


def _conv_epilogue():
    """Inference conv-epilogue fusion (BN scale/shift + ReLU in one
    Pallas pass, ops/fused_epilogue.py) for the inference/serving
    legs: BENCH_CONV_EPILOGUE pin, else the banked ``conv_epilogue_ab``
    winner, else reference."""
    return _measured_choice("BENCH_CONV_EPILOGUE",
                            ("fused", "reference"),
                            "conv_epilogue_ab", "reference")


def _compile_cache_dir():
    return os.environ.get(
        "BENCH_COMPILE_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_compile_cache"))


def _enable_compile_cache():
    """Persistent XLA compilation cache for the benchmark children,
    through the ``singa_tpu.aot`` policy object (hit/miss counters +
    LRU GC under ``BENCH_COMPILE_CACHE_BUDGET_MB``, default 2048).

    The observed TPU windows are short (~50 min) and the full 3-leg
    benchmark spends most of a first attempt compiling (ResNet-50 fp32 +
    bf16 + the LM leg each compile separately; the first window's two
    full attempts died at 900s/420s on exactly this). With the cache on
    disk, a second attempt — or a later window, even after a process or
    container restart within the round — deserializes the executables
    instead of recompiling, so the timed region starts within seconds.
    Every leg's banked record now carries the hit/miss delta
    (``_compile_stats``/``_compile_delta``), so a round's BENCH json
    shows whether its numbers were measured cold or warm."""
    try:
        budget_mb = float(os.environ.get(
            "BENCH_COMPILE_CACHE_BUDGET_MB", "2048"))
    except ValueError:
        budget_mb = 2048.0
    try:
        from singa_tpu.aot import cache as aot_cache
        aot_cache.install(aot_cache.CachePolicy(
            _compile_cache_dir(),
            size_budget_bytes=int(budget_mb * (1 << 20))))
    except Exception as e:   # cache is an optimisation, never a blocker
        print(f"bench: compile cache unavailable ({e})", file=sys.stderr)


def _compile_cache_state():
    """\"warm\" when the bench compile cache already holds entries,
    else \"cold\" — stamped on every probe record so the round's
    timeout streak can be classified (see _probe_timeout_kind: the
    probe itself never compiles; the stamp says whether the round's
    FULL ATTEMPTS could still be compile-bound)."""
    try:
        from singa_tpu.aot import cache as aot_cache
        return "warm" if aot_cache.stats(
            _compile_cache_dir())["entries"] > 0 else "cold"
    except Exception:   # noqa: BLE001 — classification is best-effort
        return "cold"


def _compile_stats():
    """Process-wide compile telemetry snapshot: persistent-cache
    hits/misses plus the ``compile_seconds`` histogram's count/sum —
    diffed around each leg so its banked record shows what the leg
    paid in compiles and whether the cache served them."""
    out = {"cache_hits": 0, "cache_misses": 0, "compiles": 0,
           "compile_seconds": 0.0}
    try:
        from singa_tpu.aot import cache as aot_cache
        snap = aot_cache.snapshot()
        out["cache_hits"] = snap["hits"]
        out["cache_misses"] = snap["misses"]
    except Exception:   # noqa: BLE001 — telemetry only
        pass
    try:
        from singa_tpu.observability import metrics as _obs
        h = _obs.default_registry().get("compile_seconds")
        if h is not None:
            for series in h.to_doc()["series"]:
                out["compiles"] += int(series.get("count", 0))
                out["compile_seconds"] += float(series.get("sum", 0.0))
    except Exception:   # noqa: BLE001 — telemetry only
        pass
    return out


def _compile_delta(before):
    after = _compile_stats()
    return {k: round(after[k] - before[k], 3) if isinstance(after[k],
                                                            float)
            else after[k] - before[k] for k in before}


def _force(x):
    """Force COMPLETION of all device work feeding ``x``.

    ``block_until_ready`` is NOT sufficient on the tunneled 'axon'
    platform this container reaches the chip through — it resolves when
    the proxy ACKs the enqueue, not when the TPU finishes (measured:
    30 "blocked" 4096^3 matmuls in ~1 ms, i.e. 40x the chip's peak).
    The one canonical recipe lives in the installed package so every
    consumer (harness, examples, profiling) shares it."""
    from singa_tpu.utils import force_completion
    return force_completion(x)


def _slope_time(step_fn, out_of, n_small, n_big):
    """Per-step seconds via a two-point slope, cancelling the constant
    readback round-trip the tunnel adds to each timed segment. Each
    segment runs its steps back-to-back (async dispatch) and ends with a
    forced scalar readback (the real completion barrier)."""

    def seg(n):
        t0 = time.perf_counter()
        r = None
        for _ in range(n):
            r = step_fn()
        _force(out_of(r))
        return time.perf_counter() - t0

    t1 = seg(n_small)
    t2 = seg(n_big)
    if t2 > t1 and n_big > n_small:
        return (t2 - t1) / (n_big - n_small)
    # slope degenerate (tunnel-latency noise swamped the short segment):
    # fall back to the long segment, which still bounds one readback RTT
    # over n_big steps
    return t2 / n_big


def _bf16_leg_dtype():
    """The dtype_name every bf16 ResNet measurement uses — the bench
    timing leg AND the probe legs that must decompose/steer the SAME
    compiled program (fusion profile, layout/stem A/B, b128, HBM).
    Default "bf16_mixed" (the policy program production training runs);
    BENCH_BF16_MODE=cast restores the legacy params-follow-bf16-input
    program for comparison. Returns (dtype_name, mode_label)."""
    mode = os.environ.get("BENCH_BF16_MODE", "bf16_mixed")
    if mode not in ("bf16_mixed", "cast"):
        print(f"bench: BENCH_BF16_MODE={mode!r} is not "
              "bf16_mixed|cast; using bf16_mixed", file=sys.stderr)
        mode = "bf16_mixed"
    return ("bfloat16" if mode == "cast" else "bf16_mixed"), mode


def _setup_resnet_step(dev, batch, image_size, depth, dtype_name,
                       layout="NCHW", stem=None, fused_optim=None):
    """Build + compile THE canonical benchmark ResNet train step (SGD
    momentum 0.9, weight_decay 1e-5, synthetic data) and return its
    step() closure — the single source for the timing legs AND the
    fusion-profile probe, so they decompose the same compiled program.

    ``dtype_name``: "float32" | "bfloat16" (legacy ad-hoc input cast:
    params follow the bf16 input) | "bf16_mixed" (the framework's
    precision policy: fp32 masters + loss scaling, bf16 compute — what
    production training actually runs).

    ``fused_optim``: True/False pins the Pallas fused optimizer-update
    path; None resolves the banked ``fused_optim_ab`` winner via
    ``_fused_optim()`` (reference when unmeasured — the kernel itself
    additionally declines off-TPU)."""
    from singa_tpu import tensor, opt
    from singa_tpu.models import resnet
    import jax.numpy as jnp
    import numpy as np

    stem = stem or _resnet_stem()[0]
    if fused_optim is None:
        fused_optim = _fused_optim()[0] == "fused"
    model = resnet.create_model(depth=depth, num_classes=10, num_channels=3,
                                layout=layout, stem=stem)
    model.set_optimizer(opt.SGD(lr=0.1, momentum=0.9, weight_decay=1e-5,
                                fused=bool(fused_optim)))

    x = np.random.randn(batch, 3, image_size, image_size).astype(np.float32)
    y = np.eye(10)[np.random.randint(0, 10, batch)].astype(np.float32)
    tx = tensor.Tensor(data=x, device=dev, dtype=tensor.float32,
                       requires_grad=False)
    if dtype_name == "bfloat16":
        tx = tx.as_type(jnp.bfloat16)
    ty = tensor.Tensor(data=y, device=dev, dtype=tensor.float32,
                       requires_grad=False)

    model.compile([tx], is_train=True, use_graph=True,
                  policy="bf16_mixed" if dtype_name == "bf16_mixed"
                  else None)

    def step():
        out, loss = model(tx, ty)
        return loss

    step.model = model   # probes read cost analysis off the same program
    return step


def _xla_step_flops(model):
    """Per-step FLOPs from XLA's cost analysis of the JUST-MEASURED
    compiled program (``Model.step_flops``) — the numerator of the
    measured-not-modeled MFU every banked leg reports alongside the
    analytic one. Costs one AOT re-lower of the already-compiled
    signature (cheap with the persistent compile cache warm); disable
    with BENCH_XLA_MFU=0. Returns None on any failure — the analytic
    MFU still stands."""
    if os.environ.get("BENCH_XLA_MFU", "1") == "0":
        return None
    try:
        return model.step_flops(compute=True)
    except Exception as e:   # noqa: BLE001 — telemetry, never a blocker
        print(f"bench: xla step-flops unavailable ({e})", file=sys.stderr)
        return None


def _timeline_capture(step_fn, force):
    """One profiled step's compute/collective/memcpy/host/idle
    decomposition (``observability.timeline`` over the SAME compiled
    program the leg just timed) — banked per leg so the MFU trajectory
    names WHAT to fix (exposed collectives vs input stalls vs
    HBM-bound fusions), not just that it moved. ``force`` blocks on
    the step output (the trace must outlive the device work). Disable
    with BENCH_TIMELINE=0; any failure degrades to None — the timing
    numbers still stand."""
    if os.environ.get("BENCH_TIMELINE", "1") == "0":
        return None
    try:
        from singa_tpu import profiling as _prof
        from singa_tpu.observability import timeline as _tl
        events = []
        _prof.measure_step_fusions(lambda: force(step_fn()),
                                   events_out=events)
        return _tl.compact(_tl.analyze(events))
    except Exception as e:   # noqa: BLE001 — telemetry, never a blocker
        print(f"bench: timeline capture unavailable ({e})",
              file=sys.stderr)
        return None


def _peak_hbm(dev):
    """Peak-HBM high-water (bytes) via the shared observability helper
    (``observability.perf.hbm_stats`` — the promoted form of the old
    ad-hoc ``memory_stats()`` read in tools/tpu_probe_extra.py). None
    off-accelerator. NOTE: the peak is a process-lifetime high-water
    mark, so within one bench process later legs see earlier legs'
    peak too — the banked number is each leg's upper bound; the
    fresh-process HBM children in tpu_probe_extra stay the precise
    per-model measurement."""
    from singa_tpu.observability import perf as _obs_perf
    stats = _obs_perf.hbm_stats(dev.jax_device)
    return stats.get("peak_bytes_in_use") if stats else None


def _measure(dev, batch, niters, warmup, image_size, depth, dtype_name,
             layout="NCHW", stem=None, extras=None, fused_optim=None):
    """Returns (images/sec, step_ms); when the caller passes an
    ``extras`` dict, ``xla_flops_per_step`` and ``peak_hbm_bytes`` are
    recorded into it (an out-param so the 2-tuple shape external
    probes consume stays stable)."""
    cc0 = _compile_stats()
    step = _setup_resnet_step(dev, batch, image_size, depth, dtype_name,
                              layout=layout, stem=stem,
                              fused_optim=fused_optim)
    loss = None
    for _ in range(warmup):
        loss = step()
    _force(loss.data)   # also warms the readback reduction

    dt = _slope_time(step, lambda l: l.data,
                     max(1, niters // 4), niters)
    if extras is not None:
        extras["xla_flops_per_step"] = _xla_step_flops(step.model)
        extras["peak_hbm_bytes"] = _peak_hbm(dev)
        extras["compile"] = _compile_delta(cc0)
        extras["timeline"] = _timeline_capture(
            step, lambda loss: _force(loss.data))
    return batch / dt, dt * 1e3


def _leg_guard(fn, timeout, name):
    """Run one benchmark leg with a thread watchdog.

    A half-dead tunnel can hang a readback INSIDE a C++ call, where
    SIGALRM never gets delivered — the 04:34 window died exactly like
    that: 25 minutes, zero output, no diagnosis. The leg runs in a
    worker thread; if it exceeds its budget the main thread raises a
    TimeoutError NAMING the leg, so the round records where it hung and
    the already-banked legs survive. The caller STOPS after a timeout:
    the abandoned thread may still occupy the exclusive-access chip, so
    any later leg would measure interleaved work and lie."""
    import threading
    box = {}

    def run():
        try:
            box["res"] = fn()
        except BaseException as e:   # noqa: BLE001 — reported, not hidden
            box["err"] = e

    t = threading.Thread(target=run, daemon=True, name=name)
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise TimeoutError(f"{name} leg hung > {timeout}s "
                           f"(readback never returned)")
    if "err" in box:
        raise box["err"]
    return box["res"]


def run_bench(batch=32, niters=50, warmup=8, image_size=224, depth=50,
              progress=None):
    from singa_tpu import device

    leg_budget = int(os.environ.get("BENCH_LEG_TIMEOUT", "900"))

    def _emit_partial(res, stage):
        if progress is not None:
            rec = dict(res)
            rec["partial"] = stage
            progress(rec)

    dev = device.create_tpu_device()
    platform = dev.jax_device.platform
    if platform != "cpu":
        # gate on the RESOLVED platform: a "tpu" child that silently
        # fell back to XLA:CPU must not persist host-AOT CPU executables
        # (they can SIGILL after a container migration); TPU executables
        # serialize portably and are where the cache pays off
        _enable_compile_cache()
    kind = getattr(dev.jax_device, "device_kind", "")
    peak = _peak_flops(kind)
    peak32 = _peak_flops(kind, dtype="fp32")
    layout, layout_src = _conv_layout()
    stem, stem_src = _resnet_stem()
    fused_mode, fused_src = _fused_optim()

    def _mfu_xla(flops_per_step, rate, units_per_step, peak_flops):
        """achieved/peak from XLA-counted per-step flops + the measured
        rate (units/s ÷ units/step = steps/s) — the measured-not-modeled
        MFU each leg banks beside its analytic estimate."""
        if not (flops_per_step and peak_flops and units_per_step):
            return None
        return flops_per_step * rate / units_per_step / peak_flops

    fp32_extras = {}
    throughput, step_ms = _leg_guard(
        lambda: _measure(dev, batch, niters, warmup, image_size,
                         depth, "float32", layout=layout, stem=stem,
                         extras=fp32_extras),
        leg_budget, "fp32")
    res = {
        "throughput": throughput,
        "step_ms": step_ms,
        "mfu": (throughput * RESNET50_TRAIN_FLOPS_PER_IMAGE / peak32
                if peak32 else None),
        "mfu_xla": _mfu_xla(fp32_extras.get("xla_flops_per_step"),
                            throughput, batch, peak32),
        # per-dtype denominator honesty: the fp32 leg's MFU is a
        # fraction of the chip's (bf16) matmul peak unless a distinct
        # denominator was supplied — see _peak_flops. Only labeled when
        # an MFU was actually computed.
        "mfu_denominator": (
            None if not peak32
            else "fp32_env_peak" if os.environ.get("BENCH_PEAK_TFLOPS_FP32")
            else "env_peak" if os.environ.get("BENCH_PEAK_TFLOPS")
            else "bf16_peak"),
        "conv_layout": layout,
        "conv_layout_src": layout_src,
        "resnet_stem": stem,
        "resnet_stem_src": stem_src,
        "fused_optim": fused_mode,
        "fused_optim_src": fused_src,
        "platform": platform,
        "device_kind": kind or "unknown",
        # distinguishes honest slope-readback records from the earlier
        # block_until_ready ones the axon tunnel inflated
        "timing": "slope-readback",
        "git": _git_rev(),
    }
    # peak HBM rides every leg's banked record (the layout/fusion A/B
    # winners carry their memory cost beside their speed; see
    # _peak_hbm's monotonicity caveat)
    if fp32_extras.get("peak_hbm_bytes"):
        res["hbm_peak_bytes"] = fp32_extras["peak_hbm_bytes"]
    # per-leg compile telemetry: what the leg paid in compiles and
    # whether the persistent cache served them (cold vs warm round)
    if fp32_extras.get("compile"):
        res["compile"] = fp32_extras["compile"]
    # per-leg step-timeline decomposition (bucket fractions +
    # exposed-comm seconds): the MFU trajectory's "what to fix" column
    if fp32_extras.get("timeline"):
        res["timeline"] = fp32_extras["timeline"]
    _emit_partial(res, "fp32")
    # bf16 variant — POLICY-DRIVEN by default: Model.compile(
    # policy="bf16_mixed") keeps fp32 masters + dynamic loss scaling and
    # runs conv/matmul compute in the MXU's native precision. This is
    # what production mixed-precision training actually executes, so the
    # banked number tracks the real win. BENCH_BF16_MODE=cast restores
    # the old ad-hoc leg (params follow a bf16 input) for comparison.
    if os.environ.get("BENCH_BF16", "1") != "0":
        leg_dtype, bf16_mode = _bf16_leg_dtype()
        res["bf16_mode"] = bf16_mode
        try:
            bf16_extras = {}
            bt, bs = _leg_guard(
                lambda: _measure(dev, batch, niters, warmup, image_size,
                                 depth, leg_dtype, layout=layout,
                                 stem=stem, extras=bf16_extras),
                leg_budget, "bf16")
            res["bf16_throughput"] = bt
            res["bf16_step_ms"] = bs
            if peak:
                res["bf16_mfu"] = bt * RESNET50_TRAIN_FLOPS_PER_IMAGE / peak
            res["bf16_mfu_xla"] = _mfu_xla(
                bf16_extras.get("xla_flops_per_step"), bt, batch, peak)
            if bf16_extras.get("peak_hbm_bytes"):
                res["bf16_hbm_peak_bytes"] = \
                    bf16_extras["peak_hbm_bytes"]
            if bf16_extras.get("compile"):
                res["bf16_compile"] = bf16_extras["compile"]
            if bf16_extras.get("timeline"):
                res["bf16_timeline"] = bf16_extras["timeline"]
        except TimeoutError as e:
            # the zombie leg thread may still hold the chip: stop here —
            # a later leg timed against it would bank a lie
            res["bf16_error"] = str(e)[:200]
            res["leg_timeout"] = "bf16"
            _emit_partial(res, "bf16")
            return res
        except Exception as e:   # the fp32 number still stands
            res["bf16_error"] = str(e)[:200]
        _emit_partial(res, "bf16")
    # transformer-LM leg (accelerator only — secondary metric exercising
    # the Pallas flash-attention path; the headline stays ResNet-50)
    if platform != "cpu" and os.environ.get("BENCH_LM", "1") != "0":
        lm_flops = _lm_train_flops_per_token(
            LM_SHAPE["d_model"], LM_SHAPE["n_layers"], LM_SHAPE["seq"],
            LM_SHAPE["vocab"])
        try:
            lm_extras = {}
            res["lm_tokens_per_sec"] = _leg_guard(
                lambda: _measure_lm(dev, extras=lm_extras),
                leg_budget, "lm")
            if peak:
                res["lm_mfu"] = \
                    res["lm_tokens_per_sec"] * lm_flops / peak
            res["lm_mfu_xla"] = _mfu_xla(
                lm_extras.get("xla_flops_per_step"),
                res["lm_tokens_per_sec"],
                lm_extras.get("tokens_per_step"), peak)
            if lm_extras.get("peak_hbm_bytes"):
                res["lm_hbm_peak_bytes"] = lm_extras["peak_hbm_bytes"]
            if lm_extras.get("compile"):
                res["lm_compile"] = lm_extras["compile"]
            if lm_extras.get("timeline"):
                res["lm_timeline"] = lm_extras["timeline"]
            # what the LM leg measured: fused-CE-head or full-logits
            # path — without this marker, banked numbers from different
            # modes would read as perf changes between rounds
            res["lm_fused_head"] = \
                os.environ.get("BENCH_LM_FUSED", "1") != "0"
        except TimeoutError as e:
            res["lm_error"] = str(e)[:200]
            res["leg_timeout"] = "lm"
            _emit_partial(res, "lm")
            return res
        except Exception as e:
            res["lm_error"] = str(e)[:200]
        _emit_partial(res, "lm")
        # bf16 LM: compute_dtype=bfloat16 puts the whole transformer
        # stack (params + attention matmuls) in MXU-native precision —
        # the LM counterpart of the CNN bf16 leg
        if os.environ.get("BENCH_LM_BF16", "1") != "0":
            try:
                lmb_extras = {}
                res["lm_bf16_tokens_per_sec"] = _leg_guard(
                    lambda: _measure_lm(dev, compute_dtype="bfloat16",
                                        extras=lmb_extras),
                    leg_budget, "lm_bf16")
                if peak:
                    res["lm_bf16_mfu"] = \
                        res["lm_bf16_tokens_per_sec"] * lm_flops / peak
                res["lm_bf16_mfu_xla"] = _mfu_xla(
                    lmb_extras.get("xla_flops_per_step"),
                    res["lm_bf16_tokens_per_sec"],
                    lmb_extras.get("tokens_per_step"), peak)
                if lmb_extras.get("peak_hbm_bytes"):
                    res["lm_bf16_hbm_peak_bytes"] = \
                        lmb_extras["peak_hbm_bytes"]
                if lmb_extras.get("compile"):
                    res["lm_bf16_compile"] = lmb_extras["compile"]
                if lmb_extras.get("timeline"):
                    res["lm_bf16_timeline"] = lmb_extras["timeline"]
            except TimeoutError as e:
                res["lm_bf16_error"] = str(e)[:200]
                res["leg_timeout"] = "lm_bf16"
            except Exception as e:
                res["lm_bf16_error"] = str(e)[:200]
            _emit_partial(res, "lm_bf16")
    # serving leg (every platform — the engine is CPU-runnable): decode
    # tok/s + p99 per-token latency of the continuous-batching engine,
    # banked per record so the serving trajectory is visible in
    # BENCH_*.json like the training legs'
    if os.environ.get("BENCH_SERVE", "1") != "0":
        try:
            res["serving"] = _leg_guard(
                lambda: _measure_serving(dev), leg_budget, "serving")
        except TimeoutError as e:
            res["serving_error"] = str(e)[:200]
            res["leg_timeout"] = "serving"
        except Exception as e:
            res["serving_error"] = str(e)[:200]
        _emit_partial(res, "serving")
    # sharded serving leg (BENCH_SERVING_SHARDED=1 opt-in: it needs a
    # ≥4-device mesh — real on TPU pods, virtual on a CPU smoke via
    # xla_force_host_platform_device_count): the GSPMD (batch × model)
    # engine's decode tok/s + per-device KV/HBM bytes, banked beside
    # the unsharded serving record so bench_report can show what
    # sharding costs (CPU: collectives unoverlapped) or buys (TPU:
    # per-chip HBM) per round
    if os.environ.get("BENCH_SERVING_SHARDED", "0") == "1":
        try:
            res["serving_sharded"] = _leg_guard(
                lambda: _measure_serving_sharded(dev), leg_budget,
                "serving_sharded")
        except TimeoutError as e:
            res["serving_sharded_error"] = str(e)[:200]
            res["leg_timeout"] = "serving_sharded"
        except Exception as e:
            res["serving_sharded_error"] = str(e)[:200]
        _emit_partial(res, "serving_sharded")
    # serving load-sweep leg: the PAGED/speculative engine driven with
    # synthetic Poisson load across slots × prefill_len × speculative_k
    # configs; banks tok/s + p99 curves per config so the serving
    # throughput push is steered by measurements, not guesses
    # (tools/bench_report.py renders the curves + winner per SLO target)
    if os.environ.get("BENCH_SERVING_SWEEP", "1") != "0":
        sweep_box = {}  # shared with the leg so a timeout keeps
        try:            # whatever configs already finished
            res["serving_sweep"] = _leg_guard(
                lambda: _measure_serving_sweep(dev, out=sweep_box),
                leg_budget, "serving_sweep")
        except TimeoutError as e:
            res["serving_sweep_error"] = str(e)[:200]
            res["leg_timeout"] = "serving_sweep"
            if sweep_box.get("configs"):
                res["serving_sweep"] = dict(sweep_box, partial=True)
        except Exception as e:
            res["serving_sweep_error"] = str(e)[:200]
            if sweep_box.get("configs"):
                res["serving_sweep"] = dict(sweep_box, partial=True)
        _emit_partial(res, "serving_sweep")
    # disaggregated-pool serving leg (BENCH_SERVING_DISAGG=1 opt-in:
    # it compiles three engines): one prefill + two decode replicas
    # behind the FleetRouter's prefix-affinity transfer path under
    # Poisson load — banks TTFT p99 on the prefill pool and per-token
    # p50/p99 on the decode pool, the SLO split disaggregation buys
    if os.environ.get("BENCH_SERVING_DISAGG", "0") == "1":
        try:
            res["serving_disagg"] = _leg_guard(
                lambda: _measure_serving_disagg(dev), leg_budget,
                "serving_disagg")
        except TimeoutError as e:
            res["serving_disagg_error"] = str(e)[:200]
            res["leg_timeout"] = "serving_disagg"
        except Exception as e:
            res["serving_disagg_error"] = str(e)[:200]
        _emit_partial(res, "serving_disagg")
    # quant leg (singa_tpu.quant): int8 weight-only inference — ResNet
    # img/s + LM tok/s + serving decode tok/s + quantized-checkpoint
    # bytes on disk, each with its MFU where one is defined. Banked and
    # regression-gated per record like the bf16 leg.
    if os.environ.get("BENCH_QUANT", "1") != "0":
        try:
            res["quant"] = _leg_guard(
                lambda: _measure_quant(dev, batch=batch,
                                       image_size=image_size,
                                       depth=depth, peak=peak),
                leg_budget, "quant")
        except TimeoutError as e:
            res["quant_error"] = str(e)[:200]
            res["leg_timeout"] = "quant"
        except Exception as e:
            res["quant_error"] = str(e)[:200]
        _emit_partial(res, "quant")
    return res


def _measure_quant(dev, batch=32, image_size=224, depth=50, niters=20,
                   warmup=3, peak=None, lm_batch=8, lm_seq=256):
    """The banked quant leg: int8 weight-only INFERENCE throughput
    (``quant.quantize_params`` + in-graph dequant — the 4x-less-HBM
    deployment form) plus the quantized serving engine and the
    bytes-on-disk shrink of a quantized checkpoint.

    MFU is reported per sub-leg against the same peak the training legs
    use (inference = 2 FLOPs/param/unit, no backward)."""
    import tempfile

    import numpy as np

    from singa_tpu import quant, tensor
    from singa_tpu.models import resnet, transformer

    out = {"batch": batch, "depth": depth, "image_size": image_size}
    cc0 = _compile_stats()

    # -- int8 ResNet inference img/s ------------------------------------
    model = resnet.create_model(depth=depth, num_classes=10,
                                num_channels=3,
                                layout=_conv_layout()[0],
                                stem=_resnet_stem()[0])
    x = np.random.RandomState(0).randn(
        batch, 3, image_size, image_size).astype(np.float32)
    tx = tensor.Tensor(data=x, device=dev, requires_grad=False)
    model.compile([tx], is_train=False, use_graph=True)
    with tempfile.TemporaryDirectory() as td:
        # fp32 twin FIRST (quantize_params is one-way), then the int8
        # archive the same save route writes once the model is quantized
        fp32_zip = os.path.join(td, "fp32.zip")
        model.save_states(fp32_zip)
        q_report = quant.quantize_params(model)
        int8_zip = os.path.join(td, "int8.zip")
        model.save_states(int8_zip)
        out["ckpt_fp32_bytes"] = os.path.getsize(fp32_zip)
        out["ckpt_int8_bytes"] = os.path.getsize(int8_zip)
        out["ckpt_ratio"] = round(
            out["ckpt_fp32_bytes"] / out["ckpt_int8_bytes"], 2)
    out["quantized_tensors"] = len(q_report)
    model.eval()
    o = None
    for _ in range(warmup):
        o = model(tx)
    _force(o.data)
    dt = _slope_time(lambda: model(tx), lambda t: t.data,
                     max(1, niters // 4), niters)
    out["resnet_img_s"] = batch / dt
    # inference: fwd only (no 3x training multiplier)
    if peak:
        out["resnet_mfu"] = out["resnet_img_s"] * \
            (RESNET50_TRAIN_FLOPS_PER_IMAGE / 3) / peak
    # conv-epilogue choice (ops/fused_epilogue.py — BN scale/shift +
    # ReLU in one pass): the kernel only fires inside a traced
    # forward on TPU, so the fused sub-leg times a JITTED inference
    # (banked as its own metric — the eager resnet_img_s trend above
    # stays comparable across rounds) and runs only where the kernel
    # can actually engage. The choice + source always bank.
    ep_mode, ep_src = _conv_epilogue()
    out["conv_epilogue"], out["conv_epilogue_src"] = ep_mode, ep_src
    if ep_mode == "fused":
        import jax as _jax
        if _jax.default_backend() == "tpu":
            from singa_tpu.ops import fused_epilogue as _fe

            def _fwd(arr):
                t = tensor.Tensor(data=arr, device=dev,
                                  requires_grad=False)
                with model._policy_scope():
                    return model.forward(t).data

            with _fe.enabled_scope(True):
                jf = _jax.jit(_fwd)
                o = None
                for _ in range(warmup):
                    o = jf(tx.data)
                _force(o)
                dt2 = _slope_time(lambda: jf(tx.data), lambda t: t,
                                  max(1, niters // 4), niters)
            out["resnet_img_s_fused_epilogue"] = batch / dt2
        else:
            out["conv_epilogue"] = "reference"
            out["conv_epilogue_note"] = \
                "fused winner banked but backend is not tpu"
    del model, tx

    # -- int8 LM inference tok/s ----------------------------------------
    import jax.numpy as jnp  # noqa: F401 (parity with other legs)
    lm = transformer.TransformerLM(
        LM_SHAPE["vocab"], d_model=LM_SHAPE["d_model"], n_heads=8,
        n_layers=LM_SHAPE["n_layers"], max_len=lm_seq, tp=False)
    ids = np.random.RandomState(0).randint(
        0, LM_SHAPE["vocab"], (lm_batch, lm_seq)).astype(np.float32)
    ti = tensor.Tensor(data=ids, device=dev, requires_grad=False)
    lm.compile([ti], is_train=False, use_graph=True)
    quant.quantize_params(lm)
    lm.eval()
    o = None
    for _ in range(warmup):
        o = lm(ti)
    _force(o.data)
    dt = _slope_time(lambda: lm(ti), lambda t: t.data,
                     max(1, niters // 4), niters)
    out["lm_tok_s"] = lm_batch * lm_seq / dt
    if peak:
        lm_fwd_flops = _lm_train_flops_per_token(
            LM_SHAPE["d_model"], LM_SHAPE["n_layers"], lm_seq,
            LM_SHAPE["vocab"]) / 3
        out["lm_mfu"] = out["lm_tok_s"] * lm_fwd_flops / peak
    del lm, ti

    # -- quantized serving decode tok/s ----------------------------------
    serve = _measure_serving(dev, policy="int8_weight_only")
    out["serving_decode_tok_s"] = serve["decode_tok_s"]
    out["serving_p99_token_s"] = serve["p99_token_s"]
    out["hbm_peak_bytes"] = _peak_hbm(dev)
    out["compile"] = _compile_delta(cc0)
    return out


def _measure_serving(dev, slots=4, max_len=96, prefill_len=16,
                     n_requests=16, new_tokens=32, policy=None):
    """The banked serving leg: decode throughput and tail token latency
    of the continuous-batching engine over a small TransformerLM.

    A private metrics registry keeps bench runs out of the process
    SLO series; the numbers come from the engine's own histograms —
    ``decode_tok_s`` is generated tokens over summed decode-tick time,
    ``p99_token_s`` the p99 of ``serve_token_seconds`` (the quantile
    summaries the snapshot now carries). The leg also asserts the
    serve-path invariant: the decode program traced exactly once."""
    import numpy as np

    from singa_tpu import tensor
    from singa_tpu.models import transformer
    from singa_tpu.observability import metrics as obs_metrics

    cc0 = _compile_stats()
    vocab = 512
    model = transformer.TransformerLM(vocab, d_model=128, n_heads=4,
                                      n_layers=2, max_len=max_len,
                                      tp=False)
    model.eval()
    model(tensor.Tensor(data=np.zeros((1, prefill_len), np.float32),
                        device=dev, requires_grad=False))
    reg = obs_metrics.MetricsRegistry()
    eng = model.compile_serving(slots=slots, max_len=max_len,
                                prefill_len=prefill_len, policy=policy,
                                registry=reg)
    rng = np.random.RandomState(0)
    futs = [eng.submit(rng.randint(1, vocab,
                                   (int(rng.randint(1, prefill_len)),)),
                       max_new_tokens=new_tokens)
            for _ in range(n_requests)]
    # warmup: compile both programs off the clock
    eng.run_until_idle()
    for f in futs:
        f.result(timeout=1)

    wave = _measure_decode_wave(
        eng, reg,
        lambda: [eng.submit(
            rng.randint(1, vocab, (int(rng.randint(1, prefill_len)),)),
            max_new_tokens=new_tokens) for _ in range(n_requests)])
    # step-timeline probe AFTER the measured wave (a profiled tick
    # inside it would decouple the token count from the observed
    # decode time): a tiny all-ticks-profiled wave banks the serving
    # decode's bucket decomposition beside the SLO numbers
    timeline = None
    if os.environ.get("BENCH_TIMELINE", "1") != "0":
        try:
            from singa_tpu.observability import timeline as _tl
            eng._profile_every = 1
            probe = [eng.submit(rng.randint(1, vocab, (4,)),
                                max_new_tokens=4) for _ in range(2)]
            eng.run_until_idle()
            for f in probe:
                f.result(timeout=1)
            timeline = _tl.compact(eng.last_timeline)
        except Exception as e:   # noqa: BLE001 — telemetry only
            print(f"bench: serving timeline probe unavailable ({e})",
                  file=sys.stderr)
    eng.stop()
    return {
        **wave,
        **({"timeline": timeline} if timeline else {}),
        "slots": slots, "new_tokens": new_tokens,
        "n_requests": n_requests,
        "policy": str(policy) if policy is not None else None,
        "hbm_peak_bytes": _peak_hbm(dev),
        "compile": _compile_delta(cc0),
    }


def _measure_decode_wave(eng, reg, submit):
    """One steady-state serving wave against an already-WARM engine:
    ``submit()`` enqueues the wave and returns its futures. The
    decode-token accounting (each prefill samples one token OUTSIDE
    any decode tick, so the throughput numerator is decode-produced
    tokens only) and the histogram-delta p50/p99 math live HERE so
    the serving and serving_sharded legs measure the same thing by
    construction. Asserts the no-retrace pin; returns the SLO dict."""
    from singa_tpu.observability.export import series_quantiles

    def _series():
        return reg.get("serve_token_seconds").to_doc()["series"][0]

    tok0 = reg.get("serve_tokens_total").total()
    pre0 = reg.get("serve_prefill_total").total()
    before = _series()
    futs = submit()
    t0 = time.perf_counter()
    eng.run_until_idle()
    wall = time.perf_counter() - t0
    for f in futs:
        f.result(timeout=1)
    info = eng.compiled_step_info()
    assert info["n_traces"] == 1, f"decode retraced: {info}"
    tok = reg.get("serve_tokens_total").total() - tok0
    tok -= reg.get("serve_prefill_total").total() - pre0
    after = _series()
    # warmup ticks carry the XLA compile: the banked numbers are the
    # STEADY-state wave, so subtract the pre-wave series
    delta = {
        "count": after["count"] - before["count"],
        "sum": after["sum"] - before["sum"],
        "buckets": [[le, ca - cb] for (le, ca), (_le, cb)
                    in zip(after["buckets"], before["buckets"])],
    }
    q = series_quantiles(delta)
    return {
        "decode_tok_s": (tok / delta["sum"]) if delta["sum"] else None,
        "p99_token_s": q.get("p99"),
        "p50_token_s": q.get("p50"),
        "wall_tok_s": tok / wall if wall > 0 else None,
    }


def _measure_serving_sharded(dev, slots=4, max_len=96, prefill_len=16,
                             n_requests=16, new_tokens=32,
                             model_shards=2):
    """The banked ``serving_sharded`` leg: the SAME small TransformerLM
    as the serving leg, compiled with ``model_shards=2`` over a
    (batch × model) GSPMD mesh — decode tok/s, per-device KV/HBM
    bytes, and a greedy token-parity spot-check against a
    single-device engine (a sharded leg that silently diverged must
    never bank a throughput number). Needs ≥ 2·model_shards devices;
    raises typed otherwise (the leg gate turns that into a
    ``serving_sharded_error`` row naming the reason)."""
    import jax
    import numpy as np

    from singa_tpu import tensor
    from singa_tpu.models import transformer
    from singa_tpu.observability import metrics as obs_metrics

    n_dev = len(jax.devices())
    if n_dev < 2 * model_shards:
        raise RuntimeError(
            f"serving_sharded needs a ≥{2 * model_shards}-device mesh "
            f"(have {n_dev}); on CPU smoke set "
            "xla_force_host_platform_device_count")
    cc0 = _compile_stats()
    vocab = 512
    model = transformer.TransformerLM(vocab, d_model=128, n_heads=4,
                                      n_layers=2, max_len=max_len,
                                      tp=False)
    model.eval()
    model(tensor.Tensor(data=np.zeros((1, prefill_len), np.float32),
                        device=dev, requires_grad=False))
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, vocab, (int(rng.randint(1, prefill_len)),))
               for _ in range(n_requests)]

    # parity spot-check (greedy, short) before the measured wave
    ref_eng = model.compile_serving(
        slots=slots, max_len=max_len, prefill_len=prefill_len,
        registry=obs_metrics.MetricsRegistry())
    ref_futs = [ref_eng.submit(p, max_new_tokens=4) for p in prompts[:4]]
    ref_eng.run_until_idle()
    ref_toks = [f.result(timeout=1)["tokens"] for f in ref_futs]
    ref_eng.stop()

    reg = obs_metrics.MetricsRegistry()
    eng = model.compile_serving(
        slots=slots, max_len=max_len, prefill_len=prefill_len,
        model_shards=model_shards, registry=reg)
    futs = [eng.submit(p, max_new_tokens=4) for p in prompts[:4]]
    eng.run_until_idle()           # warmup: compiles off the clock
    toks = [f.result(timeout=1)["tokens"] for f in futs]
    assert toks == ref_toks, "sharded greedy tokens diverged"

    wave = _measure_decode_wave(
        eng, reg,
        lambda: [eng.submit(p, max_new_tokens=new_tokens)
                 for p in prompts])
    info = eng.compiled_step_info()
    eng.stop()
    return {
        **wave,
        "slots": slots, "new_tokens": new_tokens,
        "n_requests": n_requests,
        "mesh": info["mesh"],
        "model_shards": info["model_shards"],
        "kv_per_device_bytes": info["kv_per_device_bytes"],
        "kv_global_bytes": info["kv_global_bytes"],
        "token_parity": True,
        "hbm_peak_bytes": _peak_hbm(dev),
        "compile": _compile_delta(cc0),
    }


def _measure_serving_disagg(dev, slots=4, max_len=96, prefill_len=16,
                            n_requests=24, new_tokens=32, rps=8.0,
                            seed=0):
    """The banked ``serving_disagg`` leg: the SAME small TransformerLM
    split into disaggregated pools — one prefill replica transferring
    every sealed KV snapshot to one of two decode replicas through a
    ``FleetRouter``'s prefix-affinity routing — under seeded Poisson
    load. Banks the SLO split the architecture exists for: TTFT p99
    measured on the PREFILL pool (admission + chunked prefill, no
    decode ticks competing) and per-token p50/p99 measured on the
    DECODE pool (steady decode, no prefill bubbles), plus decode
    tok/s, transfer count, and the affinity hit ratio. Half the
    prompts share a prefix so affinity has something to hit."""
    import numpy as np

    from singa_tpu import tensor
    from singa_tpu.models import transformer
    from singa_tpu.observability import metrics as obs_metrics
    from singa_tpu.observability.export import series_quantiles
    from singa_tpu.serving import FleetRouter, ServingReplica

    cc0 = _compile_stats()
    vocab = 512
    model = transformer.TransformerLM(vocab, d_model=128, n_heads=4,
                                      n_layers=2, max_len=max_len,
                                      tp=False)
    model.eval()
    model(tensor.Tensor(data=np.zeros((1, prefill_len), np.float32),
                        device=dev, requires_grad=False))
    kw = dict(slots=slots, max_len=max_len, prefill_len=prefill_len,
              kv_layout="paged", kv_block_size=4)
    preg = obs_metrics.MetricsRegistry()
    dregs = [obs_metrics.MetricsRegistry() for _ in range(2)]
    pe = model.compile_serving(pool_role="prefill", registry=preg,
                               **kw)
    des = [model.compile_serving(pool_role="decode", registry=r, **kw)
           for r in dregs]
    rreg = obs_metrics.MetricsRegistry()
    reps = [ServingReplica(pe, name="p0", registry=preg).start()]
    reps += [ServingReplica(d, name=f"d{i}",
                            registry=dregs[i]).start()
             for i, d in enumerate(des)]
    rt = FleetRouter(reps, registry=rreg)

    rng = np.random.RandomState(seed)
    shared = rng.randint(1, vocab, (max(2, prefill_len // 2),))

    def mk_prompt():
        if rng.rand() < 0.5:
            tail = rng.randint(
                1, vocab,
                (int(rng.randint(1, max(2, prefill_len
                                        - shared.size + 1))),))
            return np.concatenate([shared, tail])[:prefill_len]
        return rng.randint(1, vocab,
                           (int(rng.randint(1, prefill_len + 1)),))

    try:
        # warmup: both pools compile off the clock
        futs = [rt.submit(mk_prompt(), max_new_tokens=new_tokens,
                          timeout=120) for _ in range(2)]
        for f in futs:
            f.result(timeout=120)

        def _series(reg, name):
            # a pool replica the affinity hash hasn't routed to yet
            # has an empty histogram — treat it as all-zero
            m = reg.get(name)
            series = m.to_doc()["series"] if m is not None else []
            return series[0] if series else None

        def _delta(a, b):
            if a is None:
                return None
            if b is None:
                return dict(a, buckets=[list(x) for x in a["buckets"]])
            return {"count": a["count"] - b["count"],
                    "sum": a["sum"] - b["sum"],
                    "buckets": [[le, ca - cb] for (le, ca), (_le, cb)
                                in zip(a["buckets"], b["buckets"])]}

        def _merge(ds):
            ds = [d for d in ds if d is not None]
            return {"count": sum(d["count"] for d in ds),
                    "sum": sum(d["sum"] for d in ds),
                    "buckets": [[row[0][0],
                                 sum(r[1] for r in row)] for row
                                in zip(*(d["buckets"] for d in ds))]}

        ttft0 = _series(preg, "serve_ttft_seconds")
        tpot0 = [_series(r, "serve_token_seconds") for r in dregs]
        tok0 = sum(r.get("serve_tokens_total").total() for r in dregs)
        t0 = time.perf_counter()
        futs = []
        for _ in range(n_requests):
            futs.append(rt.submit(mk_prompt(),
                                  max_new_tokens=new_tokens,
                                  timeout=120))
            time.sleep(float(rng.exponential(1.0 / rps)))
        for f in futs:
            f.result(timeout=120)
        wall = time.perf_counter() - t0
        # the no-retrace pin, per role: decode replicas trace their
        # decode program exactly once; the prefill replica decodes
        # only on colocate fallback (0 traces when the pool is clean)
        for e in des:
            info = e.compiled_step_info()
            assert info["n_traces"] == 1, f"decode retraced: {info}"
        assert pe.compiled_step_info()["n_traces"] <= 1, \
            f"prefill-side decode retraced: {pe.compiled_step_info()}"
        tok = sum(r.get("serve_tokens_total").total()
                  for r in dregs) - tok0
        ttft_q = series_quantiles(_delta(
            _series(preg, "serve_ttft_seconds"), ttft0))
        d = _merge([_delta(_series(r, "serve_token_seconds"), t)
                    for r, t in zip(dregs, tpot0)])
        q = series_quantiles(d)
        pools = rt.pools_summary()
        return {
            "prefill_ttft_p99_s": ttft_q.get("p99"),
            "decode_p99_token_s": q.get("p99"),
            "decode_p50_token_s": q.get("p50"),
            "decode_tok_s": (tok / d["sum"]) if d["sum"] else None,
            "wall_tok_s": tok / wall if wall > 0 else None,
            "transferred": pools["transfers"]["transferred"],
            "colocate_fallback":
                pools["transfers"]["colocate_fallback"],
            "affinity_hit_ratio": pools["affinity"]["hit_ratio"],
            "slots": slots, "new_tokens": new_tokens,
            "n_requests": n_requests, "offered_rps": rps,
            "decode_replicas": len(des),
            "hbm_peak_bytes": _peak_hbm(dev),
            "compile": _compile_delta(cc0),
        }
    finally:
        for r in reps:
            r.drain(timeout=60)


# default serving_sweep grid: (kv_layout, slots, prefill_len,
# speculative_k). The ring 4×16 row is the PR-7 baseline the paged
# rows are judged against; the k>0 rows measure what speculation buys
# under the same load. BENCH_SWEEP_CONFIGS trims/extends it as
# "layout:slots:prefill:k" comma-separated triples.
SWEEP_GRID = (
    ("ring", 4, 16, 0),
    ("paged", 4, 16, 0),
    ("paged", 4, 16, 4),
    ("paged", 2, 8, 0),
    ("paged", 2, 8, 4),
)


def _parse_sweep_grid():
    env = os.environ.get("BENCH_SWEEP_CONFIGS")
    if not env:
        return SWEEP_GRID
    grid = []
    for part in env.split(","):
        try:
            lay, slots, pf, k = part.strip().split(":")
            if lay not in ("ring", "paged"):
                raise ValueError(lay)
            grid.append((lay, int(slots), int(pf), int(k)))
        except ValueError:
            print(f"bench: ignoring malformed BENCH_SWEEP_CONFIGS "
                  f"entry {part!r} (want ring|paged:slots:prefill:k)",
                  file=sys.stderr)
    return tuple(grid) or SWEEP_GRID


def _measure_serving_sweep(dev, grid=None, n_requests=12,
                           new_tokens=24, rps=None, seed=0, out=None):
    """The banked ``serving_sweep`` leg: one small TransformerLM served
    under synthetic POISSON load (seeded exponential inter-arrivals,
    open loop on the background serve thread) across a grid of
    (kv_layout, slots, prefill_len, speculative_k) configs. Each
    config banks steady-state ``decode_tok_s`` (decode tokens over
    summed tick time), ``wall_tok_s`` (tokens over the whole loaded
    window — queueing included, what the fleet actually delivers),
    tick-latency p50/p99, TTFT p99, and — for paged rows — the prefix
    cache hit count (half the generated prompts share a prefix) and
    the speculative accepted ratio. Warmup/compile happens off the
    clock (closed-loop wave before the Poisson window); the no-retrace
    pin is asserted per config like the plain serving leg."""
    import numpy as np

    from singa_tpu import tensor
    from singa_tpu.models import transformer
    from singa_tpu.observability import metrics as obs_metrics
    from singa_tpu.observability.export import series_quantiles

    grid = grid if grid is not None else _parse_sweep_grid()
    rps = float(rps if rps is not None
                else os.environ.get("BENCH_SWEEP_RPS", "8"))
    vocab = 512
    max_pf = max(cfg[2] for cfg in grid)
    model = transformer.TransformerLM(vocab, d_model=128, n_heads=4,
                                      n_layers=2,
                                      max_len=max_pf + new_tokens + 8,
                                      tp=False)
    model.eval()
    model(tensor.Tensor(data=np.zeros((1, max_pf), np.float32),
                        device=dev, requires_grad=False))
    # `out` may be a caller-shared dict: each config is banked into it
    # the moment it completes, so a _leg_guard timeout salvages every
    # config that finished instead of discarding the whole sweep
    out = out if out is not None else {}
    out.update({"n_requests": n_requests, "new_tokens": new_tokens,
                "offered_rps": rps, "poisson_seed": seed})
    out.setdefault("configs", [])
    for lay, slots, pf, spec_k in grid:
        rng = np.random.RandomState(seed)
        reg = obs_metrics.MetricsRegistry()
        kw = dict(slots=slots, max_len=pf + new_tokens,
                  prefill_len=pf, registry=reg)
        if lay == "paged":
            # block_size 4 so the generated prompts actually span
            # full blocks and the shared prefix is shareable
            kw.update(kv_layout="paged", kv_block_size=4,
                      speculative_k=spec_k)
        eng = model.compile_serving(**kw)
        shared = rng.randint(1, vocab, (max(2, pf // 2),))

        def mk_prompt():
            if rng.rand() < 0.5:
                tail = rng.randint(
                    1, vocab,
                    (int(rng.randint(1, max(2, pf - shared.size + 1))),))
                return np.concatenate([shared, tail])[:pf]
            return rng.randint(1, vocab,
                               (int(rng.randint(1, pf + 1)),))

        # warmup: compile both programs off the clock (synchronous)
        futs = [eng.submit(mk_prompt(), max_new_tokens=new_tokens)
                for _ in range(2)]
        eng.run_until_idle()
        for f in futs:
            f.result(timeout=5)

        def _series(name):
            return reg.get(name).to_doc()["series"][0]

        tok0 = reg.get("serve_tokens_total").total()
        pre0 = reg.get("serve_prefill_total").total()
        before = _series("serve_token_seconds")
        ttft_before = _series("serve_ttft_seconds")
        eng.start()
        t0 = time.perf_counter()
        futs = []
        for _ in range(n_requests):
            futs.append(eng.submit(mk_prompt(),
                                   max_new_tokens=new_tokens))
            time.sleep(float(rng.exponential(1.0 / rps)))
        for f in futs:
            f.result(timeout=120)
        wall = time.perf_counter() - t0
        info = eng.compiled_step_info()
        assert info["n_traces"] == 1, \
            f"decode retraced in sweep config {lay}:{slots}:{pf}:" \
            f"{spec_k}: {info}"
        tok = reg.get("serve_tokens_total").total() - tok0
        tok -= reg.get("serve_prefill_total").total() - pre0
        after = _series("serve_token_seconds")

        def _delta(a, b):
            return {"count": a["count"] - b["count"],
                    "sum": a["sum"] - b["sum"],
                    "buckets": [[le, ca - cb] for (le, ca), (_le, cb)
                                in zip(a["buckets"], b["buckets"])]}

        d = _delta(after, before)
        q = series_quantiles(d)
        ttft_q = series_quantiles(_delta(_series("serve_ttft_seconds"),
                                         ttft_before))
        # bank what actually RAN, not what was requested: a declined
        # layout/speculation must not label its row with the claimed
        # config (the report's winner table steers deployments on it)
        rec = {"kv_layout": info["kv_layout"], "slots": slots,
               "prefill_len": pf,
               "speculative_k": info["speculative_k"],
               "decode_tok_s": (tok / d["sum"]) if d["sum"] else None,
               "wall_tok_s": tok / wall if wall > 0 else None,
               "p99_token_s": q.get("p99"), "p50_token_s": q.get("p50"),
               "ttft_p99_s": ttft_q.get("p99")}
        if info["kv_layout"] == "paged":
            rec["prefix_cache_hits"] = \
                int(reg.get("prefix_cache_hits_total").total())
            ratio = reg.get("speculative_accepted_ratio")
            rec["speculative_accepted_ratio"] = \
                ratio.value() if ratio is not None \
                and info["speculative_k"] else None
        eng.drain(timeout=30)
        eng.stop()
        out["configs"].append(rec)
    return out


def _setup_lm_step(dev, batch=8, seq=None, compute_dtype=None):
    """Build + compile THE canonical benchmark transformer-LM train step
    and return its step() closure (single source for the timing leg and
    the HBM-footprint probe)."""
    seq = seq or LM_SHAPE["seq"]
    from singa_tpu import tensor, opt
    from singa_tpu.models import transformer
    import jax.numpy as jnp
    import numpy as np

    # fused CE head: the (B,S,32000) logits never materialise in the
    # train step (1 GiB fp32 at these shapes) — disable via
    # BENCH_LM_FUSED=0 to measure the full-logits path
    fused = os.environ.get("BENCH_LM_FUSED", "1") != "0"
    m = transformer.TransformerLM(LM_SHAPE["vocab"],
                                  d_model=LM_SHAPE["d_model"], n_heads=8,
                                  n_layers=LM_SHAPE["n_layers"],
                                  max_len=seq, tp=False,
                                  remat=False,
                                  fused_head_chunk=8192 if fused
                                  else None,
                                  compute_dtype=jnp.bfloat16
                                  if compute_dtype == "bfloat16" else None)
    m.set_optimizer(opt.SGD(lr=0.1, momentum=0.9))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, LM_SHAPE["vocab"], (batch, seq)) \
        .astype(np.float32)
    tgt = np.roll(ids, -1, 1)
    ti = tensor.Tensor(data=ids, device=dev, requires_grad=False)
    tt = tensor.Tensor(data=tgt, device=dev, requires_grad=False)
    m.compile([ti], is_train=True, use_graph=True)

    def step():
        _, loss = m(ti, tt)
        return loss

    step.model = m       # probes read cost analysis off the same program
    return step


def _measure_lm(dev, batch=8, seq=None, niters=20, warmup=3,
                compute_dtype=None, extras=None):
    seq = seq or LM_SHAPE["seq"]
    cc0 = _compile_stats()
    step = _setup_lm_step(dev, batch=batch, seq=seq,
                          compute_dtype=compute_dtype)
    loss = None
    for _ in range(warmup):
        loss = step()
    _force(loss.data)

    dt = _slope_time(step, lambda l: l.data,
                     max(1, niters // 4), niters)
    if extras is not None:
        extras["xla_flops_per_step"] = _xla_step_flops(step.model)
        extras["tokens_per_step"] = batch * seq
        extras["peak_hbm_bytes"] = _peak_hbm(dev)
        extras["compile"] = _compile_delta(cc0)
        extras["timeline"] = _timeline_capture(
            step, lambda loss: _force(loss.data))
    return batch * seq / dt


LOCK_PATH = OBS_PATH + ".lock"


class _TpuLock:
    """Cross-process mutex so the watcher's banked benchmark run and a
    live ``python bench.py`` never hold the (exclusive-access) TPU at the
    same time — concurrent init makes both measurements fail or lie.

    ``wait_s=0`` is try-lock (watcher cycles just skip); a positive wait
    polls up to that long and then proceeds anyway, because a crashed
    holder must not block the round's scored run forever."""

    def __init__(self, wait_s):
        self.wait_s = wait_s
        self.fh = None
        self.acquired = False

    def __enter__(self):
        import fcntl
        self.fh = open(LOCK_PATH, "a")
        deadline = time.time() + self.wait_s
        while True:
            try:
                fcntl.flock(self.fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
                self.acquired = True
                return self
            except OSError:
                if time.time() >= deadline:
                    return self
                time.sleep(10)

    def __exit__(self, *exc):
        import fcntl
        try:
            fcntl.flock(self.fh, fcntl.LOCK_UN)
        except OSError:
            pass
        self.fh.close()
        return False


def _record_obs(event, data):
    # watcher and bench processes both append here; a dedicated
    # short-lived write lock (NOT the long-held TPU run lock) serializes
    # the appends so a torn/interleaved line can never drop banked
    # evidence on the floor
    rec = {"ts": time.strftime("%Y-%m-%dT%H:%M:%S"), "event": event}
    rec.update(data)
    # every banked record carries the commit that produced it, so the
    # staleness gate in _measured_choice can keep trusting an old A/B
    # winner measured on exactly this code
    rec.setdefault("git", _git_rev())
    try:
        import fcntl
        with open(OBS_PATH + ".wlock", "a") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            try:
                with open(OBS_PATH, "a") as f:
                    f.write(json.dumps(rec) + "\n")
            finally:
                fcntl.flock(lk, fcntl.LOCK_UN)
    except (OSError, TypeError):
        pass


def _load_obs():
    """Observations since the LAST ``round_start`` marker (written by
    tools/tpu_watch.py at launch). Without the scoping, a benchmark
    banked in a previous round would masquerade as this round's number
    and hide a perf regression."""
    out = []
    for rec in _raw_obs():
        if rec.get("event") == "round_start":
            out = []
        else:
            out.append(rec)
    return out


def _obs_age_s(rec):
    try:
        return time.time() - time.mktime(
            time.strptime(rec["ts"], "%Y-%m-%dT%H:%M:%S"))
    except (KeyError, ValueError, OverflowError):
        return float("inf")


def _record_round_start(max_hours):
    """Write a round-boundary marker unless one younger than the round
    length already exists — a watcher RESTART mid-round must not discard
    evidence banked earlier in the same round. Returns True if a new
    round window was opened."""
    for rec in reversed(_raw_obs()):
        if rec.get("event") == "round_start":
            if _obs_age_s(rec) < max_hours * 3600:
                return False
            break
    _record_obs("round_start", {"max_hours": max_hours})
    return True


def _raw_obs():
    """All records including round_start markers (``_load_obs`` strips
    them and everything before the last one)."""
    out = []
    try:
        with open(OBS_PATH) as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return out


def smoke_main():
    """Layered <=60s-per-item hardware smoke. Each sub-result prints (and
    is flushed) as its own JSON line the moment it exists, so a parent
    that kills this child on timeout still collects everything completed
    so far. Order: cheapest evidence first."""
    import numpy as np
    t0 = time.time()
    import jax
    import jax.numpy as jnp

    def emit(obj):
        obj["t"] = round(time.time() - t0, 1)
        print(json.dumps(obj), flush=True)

    ds = jax.devices()
    d = next((x for x in ds if x.platform != "cpu"), ds[0])
    emit({"smoke": "device", "platform": d.platform,
          "device_kind": getattr(d, "device_kind", "?"),
          "n_devices": len(ds)})
    if d.platform == "cpu":
        return
    # cache only once an accelerator is confirmed (see run_bench)
    _enable_compile_cache()

    # 1. bf16 matmul: sustained TFLOP/s — is the MXU actually there?
    # A DEPENDENT chain (each matmul consumes the previous result) timed
    # with the slope method: independent dispatches + block_until_ready
    # measure only enqueue latency on the axon tunnel (see _force).
    # randn/64 keeps the chain's magnitude stable (sqrt(n)*sd == 1).
    n = 4096
    a = jnp.asarray(np.random.RandomState(0).randn(n, n) / 64.0,
                    jnp.bfloat16)
    f = jax.jit(lambda x, y: x @ y)
    tc = time.time()
    _force(f(a, a))
    compile_s = time.time() - tc

    # dependent chain via a mutable cell so _slope_time's generic
    # step/out_of signature fits; tunnel readback RTT jitters ~±10 ms,
    # so a ~500-matmul delta (~350 ms of MXU time at peak) keeps the
    # slope error in the low percent
    cell = [a]

    def step():
        cell[0] = f(cell[0], a)
        return cell[0]

    dt = _slope_time(step, lambda x: x, 25, 525)
    emit({"smoke": "matmul_bf16_4096", "compile_s": round(compile_s, 2),
          "tflops": round(2 * n ** 3 / dt / 1e12, 2),
          "timing": "slope-readback"})

    # 2. Pallas flash-attention kernel on real hardware vs an fp32
    # softmax reference — the kernels have otherwise only ever run in
    # interpreter mode on CPU CI.
    from singa_tpu.ops import attention_mod as attention
    B, H, S, D = 2, 4, 512, 64
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
               for _ in range(3))
    o = jax.jit(lambda q, k, v: attention.flash_attention(
        q, k, v, causal=True))(q, k, v)
    sc = 1.0 / math.sqrt(D)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sc
    mask = jnp.tril(jnp.ones((S, S), bool))
    ref = jnp.einsum("bhqk,bhkd->bhqd",
                     jax.nn.softmax(jnp.where(mask, scores, -jnp.inf)), v)
    err = float(jnp.max(jnp.abs(o - ref)))
    # both the kernel and the jnp reference run their matmuls through
    # the MXU's bf16 multiply passes with different blocking, so the
    # spread between them is O(1e-2) on randn inputs (measured 6.4e-3
    # on v5e); the bound catches wrong MATH, not rounding-path drift
    emit({"smoke": "flash_attention_pallas_maxerr", "value": err,
          "ok": bool(err < 2e-2)})

    # 3. one small real train step through the full Model/graph stack
    from singa_tpu import device as sdev
    dev = sdev.create_tpu_device()
    thr, ms = _measure(dev, batch=16, niters=5, warmup=1, image_size=64,
                       depth=18, dtype_name="float32")
    emit({"smoke": "resnet18_64px_b16", "step_ms": round(ms, 2),
          "images_per_sec": round(thr, 1)})


def _attempt_smoke(timeout=300):
    """Run the smoke child; parse every JSON line it managed to print,
    INCLUDING partial output from a timed-out child."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", "smoke"],
            capture_output=True, text=True, timeout=timeout)
        out = proc.stdout or ""
    except subprocess.TimeoutExpired as e:
        out = e.stdout or ""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
    lines = []
    for line in out.strip().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "smoke" in rec:
            lines.append(rec)
    return lines


def child_main(platform):
    """Run the real benchmark; print ONE result JSON line on stdout."""
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        batch = int(os.environ.get("BENCH_BATCH", "4"))
        niters = int(os.environ.get("BENCH_ITERS", "2"))
        os.environ.setdefault("BENCH_BF16", "0")  # CPU emulated bf16 is slow
        warmup = 1
    else:
        batch = int(os.environ.get("BENCH_BATCH", "32"))
        niters = int(os.environ.get("BENCH_ITERS", "50"))
        warmup = 8
    # each completed leg prints (and flushes) immediately: a parent
    # that kills this child on timeout still collects the finished legs
    res = run_bench(batch=batch, niters=niters, warmup=warmup,
                    progress=lambda rec: print(json.dumps(rec), flush=True))
    print(json.dumps(res), flush=True)
    # hard exit: a leg-guard's abandoned thread can still sit inside a
    # JAX runtime call, and interpreter finalization racing it could
    # crash AFTER the result printed — which would demote this complete
    # run to partial_crash in the parent
    sys.stdout.flush()
    os._exit(0)


def _last_result_line(out, marker_key=None, marker_val=None):
    """Newest JSON line on ``out`` that looks like a benchmark result
    (has "throughput"), optionally stamped with a partial marker."""
    for line in reversed((out or "").strip().splitlines()):
        try:
            res = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(res, dict) and "throughput" in res:
            if marker_key:
                res[marker_key] = marker_val
            return res
    return None


def _is_complete(rec):
    """A full benchmark, not a salvaged or leg-timeout prefix of one."""
    return not (rec.get("partial") or rec.get("partial_timeout")
                or rec.get("partial_crash") or rec.get("leg_timeout"))


def _n_legs(rec):
    return sum(1 for k in ("throughput", "bf16_throughput",
                           "lm_tokens_per_sec", "lm_bf16_tokens_per_sec")
               if rec.get(k) is not None)


def _attempt(platform, timeout):
    """One child attempt; returns the parsed result dict or an error str.

    On timeout or a mid-run crash (both observed tunnel failure modes),
    the last complete leg the child printed is salvaged and returned
    with a partial marker — a 3-leg benchmark that finished fp32+bf16
    but not the LM leg still banks those numbers."""
    env = dict(os.environ)
    # the in-child per-leg watchdog must fire (and name the hung leg)
    # BEFORE the parent's hard kill silences the child — derive its
    # budget from this attempt's timeout unless the user pinned one
    env.setdefault("BENCH_LEG_TIMEOUT", str(max(120, int(timeout * 0.55))))
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", platform],
            capture_output=True, text=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired as e:
        out = e.stdout or ""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        res = _last_result_line(out, "partial_timeout",
                                f"killed after {timeout}s")
        return res, None if res else f"timeout after {timeout}s"
    if proc.returncode != 0:
        res = _last_result_line(proc.stdout, "partial_crash",
                                f"child rc={proc.returncode}")
        if res is not None:
            return res, None
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return None, f"rc={proc.returncode}: {tail[-1] if tail else '?'}"
    res = _last_result_line(proc.stdout)
    return (res, None) if res is not None \
        else (None, "no result JSON in child output")


def _probe_tpu(timeout):
    """Cheap liveness check: can a child process see a non-CPU device at
    all? Bounds the cost of a hung backend init to ``timeout`` seconds
    instead of a full benchmark attempt.

    Returns (status, err) with status one of:
      "ok"      — accelerator visible
      "cpu"     — backend initialised and explicitly reported CPU-only
      "timeout" — init hung (tunnel down, or a very slow cold start)
      "error"   — probe crashed (transient import/init failure — says
                  nothing about whether a chip exists)
    Only "cpu" is a *confirmed* absence; callers should still make one
    bounded real attempt for "timeout"/"error"."""
    code = ("import jax\n"
            "ds = jax.devices()\n"
            "print('PROBE_OK' if any(d.platform != 'cpu' for d in ds)"
            " else 'PROBE_CPU')\n")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired:
        return "timeout", f"probe timeout after {timeout}s"
    if "PROBE_OK" in proc.stdout:
        return "ok", None
    if "PROBE_CPU" in proc.stdout:
        return "cpu", "no accelerator visible"
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return "error", tail[-1] if tail else "probe produced no output"


def _dead_probe_streak():
    """Trailing consecutive probe TIMEOUTS banked this round. Any
    non-timeout probe outcome (ok / cpu / error — each proves the
    backend at least answered) breaks the streak; non-probe records are
    skipped, so a cooldown marker or a banked smoke doesn't reset it."""
    n = 0
    for o in reversed(_load_obs()):
        if o.get("event") != "probe":
            continue
        if o.get("status") == "timeout":
            n += 1
        else:
            break
    return n


def _probe_timeout_kind():
    """Classify the trailing probe-timeout streak for the round
    report. The probe child itself runs only ``jax.devices()`` —
    backend init, zero XLA compiles — so a probe timeout is never
    compile time; the ambiguity the stamp resolves is what the
    ROUND's timeouts mean: ``dead_tunnel`` when any timeout in the
    streak ran against a WARM cache (the round's expensive work — the
    full benchmark attempts whose compiles historically blew their
    budgets — cannot be compile-bound either, so a backend that still
    cannot even init is down, full stop); ``cold_compile_possible``
    when every timeout ran cold — the probe wasn't compiling, but the
    round's full attempts may have been, so the banked round numbers
    (and any attempt-timeout records beside this streak) carry the
    cold-compile caveat. Once the cache is warm, every future timeout
    is diagnostic — which is how the warm cache retires BENCH_r05's
    73-timeout class of ambiguous rounds."""
    any_warm = False
    any_cold = False
    for o in reversed(_load_obs()):
        if o.get("event") != "probe":
            continue
        if o.get("status") != "timeout":
            break
        if o.get("compile_cache") == "warm":
            any_warm = True
        else:
            any_cold = True
    if any_warm or not any_cold:
        return "dead_tunnel"
    return "cold_compile_possible"


def _probe_cooldown():
    """Dead-tunnel fast-fail: BENCH_r05 burned ~11.5h of round budget on
    73 consecutive probe timeouts — every cycle paid the full 120–180s
    child wait against a tunnel that never answered. After
    BENCH_PROBE_FASTFAIL consecutive timeouts (default 6 ≈ the first
    ~45 min of a dead round on the watcher cadence) the tunnel is
    treated as down: bench.py banks a ``probe_cooldown`` record and
    falls straight to the banked/CPU path; tools/tpu_watch.py drops to
    short probes on a slow cadence (a probe that ever succeeds breaks
    the streak and restores full service). Returns the streak length
    when the cooldown applies, else 0. BENCH_FORCE_PROBE=1 forces a
    full re-probe regardless."""
    if os.environ.get("BENCH_FORCE_PROBE", "0") == "1":
        return 0
    try:
        limit = int(os.environ.get("BENCH_PROBE_FASTFAIL", "6"))
    except ValueError:
        print("bench: BENCH_PROBE_FASTFAIL is not an integer; using 6",
              file=sys.stderr)
        limit = 6
    if limit <= 0:
        return 0
    n = _dead_probe_streak()
    return n if n >= limit else 0


def _tpu_phase(errors):
    """Probe + smoke + full attempts. Returns (res, smoke_lines)."""
    res = None
    smoke = []
    streak = _probe_cooldown()
    if streak:
        kind = _probe_timeout_kind()
        _record_obs("probe_cooldown",
                    {"consecutive_timeouts": streak, "kind": kind,
                     "src": "bench"})
        errors.append(
            f"tpu probe skipped: {streak} consecutive probe timeouts "
            f"banked this round ({kind}; BENCH_FORCE_PROBE=1 to "
            "re-probe)")
        return None, []
    # a hung backend init must not eat the whole time budget: probe first
    # (generous enough for a slow cold start), and only run the real
    # benchmark when a chip is actually visible
    cache_state = _compile_cache_state()
    status, perr = _probe_tpu(180)
    _record_obs("probe", {"status": status, "err": perr, "src": "bench",
                          "compile_cache": cache_state})
    if status != "ok":
        errors.append(f"tpu probe#1: {perr}")
        print(f"bench: tpu probe failed ({perr}), retrying",
              file=sys.stderr)
        time.sleep(10)
        status, perr = _probe_tpu(180)
        # re-sampled: probe #1's child may have warmed the cache
        # before dying, and a stale "cold" stamp here would soften
        # the dead-tunnel classification
        _record_obs("probe", {"status": status, "err": perr, "src": "bench",
                              "compile_cache": _compile_cache_state()})
        if status != "ok":
            errors.append(f"tpu probe#2: {perr}")
    if status == "ok":
        # layered: bank the cheap smoke evidence FIRST, so a tunnel that
        # drops mid-benchmark still leaves hardware numbers behind
        smoke = _attempt_smoke(300)
        for rec in smoke:
            _record_obs("smoke", rec)
        # two full attempts: the backend is observably flaky mid-run too.
        # A salvaged PARTIAL result must not cancel the retry — with the
        # persistent compile cache warm from attempt 1, attempt 2 skips
        # straight to the timed region and usually completes the
        # remaining legs. Keep the best partial as the fallback.
        best_partial = None
        for i, timeout in enumerate([1500, 600]):
            res, err = _attempt("tpu", timeout)
            if res is not None:
                _record_obs("bench", res)
                if _is_complete(res):
                    break
                if best_partial is None or _n_legs(res) >= \
                        _n_legs(best_partial):
                    best_partial = res
                err = res.get("partial_timeout") or res.get("partial_crash")
                res = None
            errors.append(f"tpu#{i + 1}: {err}")
            print(f"bench: tpu attempt {i + 1} failed ({err})",
                  file=sys.stderr)
        if res is None:
            res = best_partial
    elif status in ("timeout", "error"):
        # probe inconclusive — a hung init OR a transient probe crash,
        # neither of which confirms a cpu-only world: one bounded real
        # attempt regardless
        res, err = _attempt("tpu", 600)
        if res is not None:
            _record_obs("bench", res)
        else:
            errors.append(f"tpu inconclusive-probe attempt: {err}")
            print(f"bench: inconclusive-probe tpu attempt failed ({err})",
                  file=sys.stderr)
    return res, smoke


def main():
    errors = []
    # serialize against the watcher: if it is mid-benchmark on a live
    # tunnel, waiting for it both frees the chip for our run and (worst
    # case) means its result is banked for us to report. The wait must
    # exceed the watcher's worst-case lock hold (120s probe + 300s smoke
    # + 1500s full bench = 1920s)
    with _TpuLock(wait_s=2100) as lock:
        if not lock.acquired:
            print("bench: tpu lock busy past deadline, proceeding",
                  file=sys.stderr)
        res, smoke = _tpu_phase(errors)
    obs = _load_obs()
    max_age = float(os.environ.get("BENCH_BANKED_MAX_AGE_H", "14")) * 3600
    res, live = _fold_banked(res, obs, max_age, errors)
    if not smoke:
        smoke = [o for o in obs if o.get("event") == "smoke"
                 and _obs_age_s(o) < max_age]
    if res is None:
        # last resort: a CPU number, clearly labeled, so the round still
        # records a real measurement instead of a traceback
        res, err = _attempt("cpu", 480)
        if res is None:
            errors.append(f"cpu: {err}")
            print(json.dumps({
                "metric": "resnet50_synthetic_images_per_sec_per_chip",
                "value": None, "unit": "images/sec", "vs_baseline": 0.0,
                "error": "; ".join(errors),
            }))
            return
    _emit_report(res, live, smoke, obs, errors)


def _fold_banked(res, obs, max_age, errors):
    """Fold this round's banked observations into the live result.
    Returns (result, live): the record to report and whether it came
    from the live run just made (vs banked earlier by the watcher)."""
    live = res is not None
    if res is None or not _is_complete(res):
        # the tunnel is down NOW (or only yielded a partial run) — but
        # the round-long watcher may have banked a full benchmark during
        # an earlier window. Both the round_start marker (via _load_obs)
        # and an age cap guard against reporting a PREVIOUS round's
        # number.
        banked = [o for o in obs if o.get("event") == "bench"
                  and o.get("platform") not in (None, "cpu")
                  and _obs_age_s(o) < max_age]
        # block_until_ready-timed records are inflated on the axon
        # tunnel (it ACKs enqueue, not completion): prefer slope-readback
        # records and, failing that, carry the old record only with an
        # explicit suspect marker
        honest = [o for o in banked
                  if o.get("timing") == "slope-readback"]
        if honest:
            banked = honest
        # a COMPLETE banked benchmark beats a newer salvaged partial —
        # completeness first, then leg count, then recency (mirrors
        # _tpu_phase's best-partial rule). (A live partial is itself
        # banked by _tpu_phase, so it sits in `banked` too and wins only
        # when nothing more complete exists.)
        complete = [o for o in banked if _is_complete(o)]
        pool = complete or banked
        pick = max(enumerate(pool),
                   key=lambda p: (_n_legs(p[1]), p[0]))[1] if pool \
            else None
        keep_live = (res is not None and pick is not None
                     and not _is_complete(pick)
                     and _n_legs(res) >= _n_legs(pick))
        if pick is not None and not keep_live:
            if res is not None:
                errors.append(
                    "live run was partial; reporting the more complete "
                    "benchmark banked earlier this round instead")
            res = dict(pick)
            res["measured_at"] = res.pop("ts")
            live = False
            if res.get("timing") != "slope-readback":
                res["timing_suspect"] = (
                    "block_until_ready timing; the tunnel inflates it — "
                    "treat as an upper bound, not a measurement")
    return res, live


def _fold_extras(obs):
    """Newest banked success record per extra-probe leg, folded into the
    round artifact so the judge sees every hardware measurement (layout
    A/B, long-context, KV decode, HBM peaks, fusion profile) in ONE
    parsed JSON — not just the 4-leg headline."""
    keep = {m for markers in EXTRA_SUCCESS_MARKERS.values()
            for m in markers}
    latest = {}
    for o in obs:
        if o.get("event") == "extra" and o.get("extra") in keep \
                and o.get("error") is None:
            latest[o["extra"]] = {k: v for k, v in o.items()
                                  if k not in ("event", "extra")}
    # salvaged A/B prefixes: a `{leg}_partial` record (the probe's
    # box-banking contract — completed configs survive a hung sweep)
    # folds ONLY while no full success exists, and keeps its partial
    # flag so the judge never mistakes half an A/B for a winner
    for o in obs:
        mk = str(o.get("extra") or "")
        if o.get("event") == "extra" and mk.endswith("_partial") \
                and mk[:-len("_partial")] in keep \
                and mk[:-len("_partial")] not in latest:
            latest[mk] = {k: v for k, v in o.items()
                          if k not in ("event", "extra")}
    # fusion profiles are large: fold a compact summary (total + top-3)
    for o in obs:
        if o.get("event") == "extra" \
                and o.get("extra") in ("resnet50_bf16_fusion_profile",
                                       "lm_bf16_fusion_profile") \
                and o.get("error") is None:
            latest[o["extra"]] = {
                "ts": o.get("ts"),
                "total_measured_s": o.get("total_measured_s"),
                "top": (o.get("top") or [])[:3],
            }
    return latest


def _emit_report(res, live, smoke, obs, errors):
    baseline = float(os.environ.get("BENCH_BASELINE", "0") or 0)
    vs = res["throughput"] / baseline if baseline > 0 else 1.0
    out = {
        "metric": "resnet50_synthetic_images_per_sec_per_chip",
        "value": round(res["throughput"], 2),
        "unit": "images/sec",
        "vs_baseline": round(vs, 3),
        "step_ms": round(res["step_ms"], 2),
        "platform": res["platform"],
        "device_kind": res["device_kind"],
    }
    if res.get("measured_at"):
        out["measured_at"] = res["measured_at"]
        out["live"] = False
    if res["platform"] == "cpu":
        # tiny batch, 2 timed iters, compile-dominated: a liveness
        # fallback, NOT a performance trend point — do not compare
        # rounds on it
        out["indicative"] = False
    # secondary measurements AND integrity markers ride along so the
    # round artifact records the full picture (MFU, bf16 leg, LM
    # tokens/s, timing method, partial/suspect flags), not just the
    # headline images/sec
    for k in ("mfu", "mfu_xla", "mfu_denominator", "conv_layout",
              "conv_layout_src", "resnet_stem", "resnet_stem_src",
              "fused_optim", "fused_optim_src", "git",
              "bf16_throughput", "bf16_step_ms", "bf16_mfu",
              "bf16_mfu_xla", "bf16_mode",
              "bf16_error", "lm_tokens_per_sec", "lm_bf16_tokens_per_sec",
              "lm_mfu", "lm_mfu_xla", "lm_bf16_mfu", "lm_bf16_mfu_xla",
              "lm_error", "lm_bf16_error",
              "lm_fused_head", "timing", "timing_suspect",
              "partial", "partial_timeout", "partial_crash",
              "leg_timeout",
              # per-leg ride-alongs the trajectory report reads
              # (tools/bench_report.py): step-timeline decompositions,
              # peak HBM, compile deltas, and the serving/quant leg
              # blocks — run_bench sets them on res, and without this
              # list they would die here instead of reaching the
              # banked BENCH_rNN.json
              "timeline", "bf16_timeline", "lm_timeline",
              "lm_bf16_timeline",
              "hbm_peak_bytes", "bf16_hbm_peak_bytes",
              "lm_hbm_peak_bytes", "lm_bf16_hbm_peak_bytes",
              "compile", "bf16_compile", "lm_compile",
              "lm_bf16_compile",
              "serving", "serving_error", "quant", "quant_error",
              "serving_sweep", "serving_sweep_error",
              "serving_sharded", "serving_sharded_error"):
        if res.get(k) is not None:
            out[k] = round(res[k], 4) if isinstance(res[k], float) else res[k]
    extras = _fold_extras(obs)
    if extras:
        out["extra_measurements"] = extras
    if smoke:
        # one stable shape for the field, whether the records came from
        # the live child (no ts/event) or from the banked jsonl
        norm = [{k: v for k, v in rec.items() if k != "event"}
                for rec in smoke if rec.get("smoke") != "device"]
        if norm:
            out["tpu_smoke"] = norm[-8:]
    probes = [o for o in obs if o.get("event") == "probe"]
    if probes and out["platform"] == "cpu":
        out["tpu_probes"] = {
            "n": len(probes),
            "first": probes[0].get("ts"), "last": probes[-1].get("ts"),
            "statuses": {s: sum(1 for o in probes if o.get("status") == s)
                         for s in {o.get("status") for o in probes}},
        }
    if not live and out["platform"] != "cpu":
        # "live" is False both when the tunnel was down AND when a live
        # partial was superseded by a better banked record — say which,
        # so the round artifact doesn't fabricate a tunnel outage
        if any("live run was partial" in e for e in errors):
            out["note"] = ("live run was partial; reporting the more "
                           "complete benchmark banked earlier this round "
                           "by tools/tpu_watch.py")
        else:
            out["note"] = ("benchmark banked earlier this round by "
                           "tools/tpu_watch.py; tunnel was down at "
                           "report time")
    if errors:
        out["retries"] = errors
    print(json.dumps(out))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        target = sys.argv[2] if len(sys.argv) > 2 else "tpu"
        if target == "smoke":
            smoke_main()
        else:
            child_main(target)
    else:
        main()
