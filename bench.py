"""Benchmark harness: ResNet-50 synthetic-data training throughput.

The reference's headline harness (examples/cnn/benchmark.py:85-87) measures
`throughput = niters * batch * world / (end - start)` on ResNet-50 with
synthetic data. The reference publishes no numbers (BASELINE.md), so
``vs_baseline`` reports against our own first recorded TPU run when one
exists (BENCH_BASELINE env), else 1.0.

Structure: the parent process is a pure orchestrator — it probes TPU
liveness in a bounded child (a hung backend init must not eat the time
budget), runs the real benchmark in a child subprocess with a hard timeout
(two attempts — the backend can also fail transiently mid-run), and falls
back to a clearly-labeled CPU measurement as a last resort, so this script
ALWAYS exits 0 with ONE parseable JSON line:
{"metric", "value", "unit", "vs_baseline", ...extras}.
"""

import json
import os
import subprocess
import sys
import time

# ResNet-50 @224x224: ~4.09 GMACs forward per image; 2 flops/MAC; a training
# step (fwd + bwd wrt activations + bwd wrt weights) is ~3x forward.
RESNET50_TRAIN_FLOPS_PER_IMAGE = 4.09e9 * 2 * 3

# Peak dense fp32/bf16 FLOP/s per chip by TPU generation (public figures),
# for the MFU estimate. Overridable via BENCH_PEAK_TFLOPS.
PEAK_FLOPS_BY_KIND = [
    ("v6", 918e12), ("v5p", 459e12), ("v5e", 197e12), ("v5 lite", 197e12),
    ("v5lite", 197e12), ("v5", 459e12), ("v4", 275e12), ("v3", 123e12),
    ("v2", 45e12),
]


def _peak_flops(device_kind: str):
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = (device_kind or "").lower()
    for tag, peak in PEAK_FLOPS_BY_KIND:
        if tag in kind:
            return peak
    return None


def _measure(dev, batch, niters, warmup, image_size, depth, dtype_name):
    from singa_tpu import tensor, opt, device  # noqa: F401
    from singa_tpu.models import resnet
    import jax.numpy as jnp
    import numpy as np

    model = resnet.create_model(depth=depth, num_classes=10, num_channels=3)
    model.set_optimizer(opt.SGD(lr=0.1, momentum=0.9, weight_decay=1e-5))

    x = np.random.randn(batch, 3, image_size, image_size).astype(np.float32)
    y = np.eye(10)[np.random.randint(0, 10, batch)].astype(np.float32)
    tx = tensor.Tensor(data=x, device=dev, dtype=tensor.float32,
                       requires_grad=False)
    if dtype_name == "bfloat16":
        tx = tx.as_type(jnp.bfloat16)
    ty = tensor.Tensor(data=y, device=dev, dtype=tensor.float32,
                       requires_grad=False)

    model.compile([tx], is_train=True, use_graph=True)

    for _ in range(warmup):
        out, loss = model(tx, ty)
    loss.data.block_until_ready()

    start = time.perf_counter()
    for _ in range(niters):
        out, loss = model(tx, ty)
    loss.data.block_until_ready()
    end = time.perf_counter()
    return (niters * batch / (end - start),
            (end - start) / niters * 1e3)


def run_bench(batch=32, niters=50, warmup=8, image_size=224, depth=50):
    from singa_tpu import device

    dev = device.create_tpu_device()
    platform = dev.jax_device.platform
    peak = _peak_flops(getattr(dev.jax_device, "device_kind", ""))

    throughput, step_ms = _measure(dev, batch, niters, warmup, image_size,
                                   depth, "float32")
    res = {
        "throughput": throughput,
        "step_ms": step_ms,
        "mfu": (throughput * RESNET50_TRAIN_FLOPS_PER_IMAGE / peak
                if peak else None),
        "platform": platform,
        "device_kind": getattr(dev.jax_device, "device_kind", "unknown"),
    }
    # bf16 variant: params follow the input dtype, so the whole train step
    # (fwd+bwd+SGD) runs in the MXU's native precision — the TPU-first
    # counterpart of the reference's fp16 precision flag
    if os.environ.get("BENCH_BF16", "1") != "0":
        try:
            bt, bs = _measure(dev, batch, niters, warmup, image_size,
                              depth, "bfloat16")
            res["bf16_throughput"] = bt
            res["bf16_step_ms"] = bs
            if peak:
                res["bf16_mfu"] = bt * RESNET50_TRAIN_FLOPS_PER_IMAGE / peak
        except Exception as e:   # the fp32 number still stands
            res["bf16_error"] = str(e)[:200]
    # transformer-LM leg (accelerator only — secondary metric exercising
    # the Pallas flash-attention path; the headline stays ResNet-50)
    if platform != "cpu" and os.environ.get("BENCH_LM", "1") != "0":
        try:
            res["lm_tokens_per_sec"] = _measure_lm(dev)
        except Exception as e:
            res["lm_error"] = str(e)[:200]
    return res


def _measure_lm(dev, batch=8, seq=1024, niters=20, warmup=3):
    from singa_tpu import tensor, opt
    from singa_tpu.models import transformer
    import numpy as np

    m = transformer.TransformerLM(32000, d_model=512, n_heads=8,
                                  n_layers=6, max_len=seq, tp=False,
                                  remat=False)
    m.set_optimizer(opt.SGD(lr=0.1, momentum=0.9))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 32000, (batch, seq)).astype(np.float32)
    tgt = np.roll(ids, -1, 1)
    ti = tensor.Tensor(data=ids, device=dev, requires_grad=False)
    tt = tensor.Tensor(data=tgt, device=dev, requires_grad=False)
    m.compile([ti], is_train=True, use_graph=True)
    for _ in range(warmup):
        _, loss = m(ti, tt)
    loss.data.block_until_ready()
    start = time.perf_counter()
    for _ in range(niters):
        _, loss = m(ti, tt)
    loss.data.block_until_ready()
    return niters * batch * seq / (time.perf_counter() - start)


def child_main(platform):
    """Run the real benchmark; print ONE result JSON line on stdout."""
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        batch = int(os.environ.get("BENCH_BATCH", "4"))
        niters = int(os.environ.get("BENCH_ITERS", "2"))
        os.environ.setdefault("BENCH_BF16", "0")  # CPU emulated bf16 is slow
        warmup = 1
    else:
        batch = int(os.environ.get("BENCH_BATCH", "32"))
        niters = int(os.environ.get("BENCH_ITERS", "50"))
        warmup = 8
    res = run_bench(batch=batch, niters=niters, warmup=warmup)
    print(json.dumps(res), flush=True)


def _attempt(platform, timeout):
    """One child attempt; returns the parsed result dict or an error str."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", platform],
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout}s"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return None, f"rc={proc.returncode}: {tail[-1] if tail else '?'}"
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line), None
        except json.JSONDecodeError:
            continue
    return None, "no JSON in child output"


def _probe_tpu(timeout):
    """Cheap liveness check: can a child process see a non-CPU device at
    all? Bounds the cost of a hung backend init to ``timeout`` seconds
    instead of a full benchmark attempt."""
    code = ("import jax\n"
            "ds = jax.devices()\n"
            "print('PROBE_OK' if any(d.platform != 'cpu' for d in ds)"
            " else 'PROBE_CPU')\n")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired:
        return False, f"probe timeout after {timeout}s"
    if "PROBE_OK" in proc.stdout:
        return True, None
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return False, tail[-1] if tail else "no accelerator visible"


def main():
    errors = []
    res = None
    # a hung backend init must not eat the whole time budget: probe first
    # (generous enough for a slow cold start), and only run the real
    # benchmark when a chip is actually visible
    alive, perr = _probe_tpu(180)
    if not alive:
        errors.append(f"tpu probe#1: {perr}")
        print(f"bench: tpu probe failed ({perr}), retrying",
              file=sys.stderr)
        time.sleep(10)
        alive, perr = _probe_tpu(180)
        if not alive:
            errors.append(f"tpu probe#2: {perr}")
    if alive:
        # two attempts: the backend is observably flaky mid-run too
        for i, timeout in enumerate([900, 420]):
            res, err = _attempt("tpu", timeout)
            if res is not None:
                break
            errors.append(f"tpu#{i + 1}: {err}")
            print(f"bench: tpu attempt {i + 1} failed ({err})",
                  file=sys.stderr)
    elif perr and "timeout" in perr:
        # a probe TIMEOUT (vs "no accelerator visible") may be a very
        # slow init rather than a hang: one bounded real attempt
        res, err = _attempt("tpu", 600)
        if res is None:
            errors.append(f"tpu slow-init attempt: {err}")
            print(f"bench: slow-init tpu attempt failed ({err})",
                  file=sys.stderr)
    if res is None:
        # last resort: a CPU number, clearly labeled, so the round still
        # records a real measurement instead of a traceback
        res, err = _attempt("cpu", 480)
        if res is None:
            errors.append(f"cpu: {err}")
            print(json.dumps({
                "metric": "resnet50_synthetic_images_per_sec_per_chip",
                "value": None, "unit": "images/sec", "vs_baseline": 0.0,
                "error": "; ".join(errors),
            }))
            return
    baseline = float(os.environ.get("BENCH_BASELINE", "0") or 0)
    vs = res["throughput"] / baseline if baseline > 0 else 1.0
    out = {
        "metric": "resnet50_synthetic_images_per_sec_per_chip",
        "value": round(res["throughput"], 2),
        "unit": "images/sec",
        "vs_baseline": round(vs, 3),
        "step_ms": round(res["step_ms"], 2),
        "platform": res["platform"],
        "device_kind": res["device_kind"],
    }
    if res.get("mfu") is not None:
        out["mfu"] = round(res["mfu"], 4)
    for k in ("bf16_throughput", "bf16_step_ms", "bf16_mfu", "bf16_error"):
        if res.get(k) is not None:
            out[k] = round(res[k], 4) if isinstance(res[k], float) else res[k]
    if errors:
        out["retries"] = errors
    print(json.dumps(out))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child_main(sys.argv[2] if len(sys.argv) > 2 else "tpu")
    else:
        main()
