"""Benchmark harness: ResNet-50 synthetic-data training throughput.

The reference's headline harness (examples/cnn/benchmark.py:85-87) measures
`throughput = niters * batch * world / (end - start)` on ResNet-50 with
synthetic data. The reference publishes no numbers (BASELINE.md), so
``vs_baseline`` reports against our own first recorded TPU run when one
exists (BENCH_BASELINE env or the default below), else 1.0.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import time

import numpy as np


def run_bench(batch=32, niters=50, warmup=8, image_size=224, depth=50,
              dtype="float32"):
    from singa_tpu import tensor, opt, device
    from singa_tpu.models import resnet

    dev = device.create_tpu_device()
    model = resnet.create_model(depth=depth, num_classes=10, num_channels=3)
    model.set_optimizer(opt.SGD(lr=0.1, momentum=0.9, weight_decay=1e-5))

    x = np.random.randn(batch, 3, image_size, image_size).astype(np.float32)
    y = np.eye(10)[np.random.randint(0, 10, batch)].astype(np.float32)
    tx = tensor.Tensor(data=x, device=dev, dtype=tensor.float32,
                       requires_grad=False)
    ty = tensor.Tensor(data=y, device=dev, dtype=tensor.float32,
                       requires_grad=False)

    model.compile([tx], is_train=True, use_graph=True)

    for _ in range(warmup):
        out, loss = model(tx, ty)
    loss.data.block_until_ready()

    start = time.perf_counter()
    for _ in range(niters):
        out, loss = model(tx, ty)
    loss.data.block_until_ready()
    end = time.perf_counter()

    throughput = niters * batch / (end - start)
    step_ms = (end - start) / niters * 1e3
    return throughput, step_ms


def main():
    niters = int(os.environ.get("BENCH_ITERS", "50"))
    batch = int(os.environ.get("BENCH_BATCH", "32"))
    throughput, step_ms = run_bench(batch=batch, niters=niters)
    # No published reference number exists (BASELINE.md); compare against a
    # recorded prior run when provided.
    baseline = float(os.environ.get("BENCH_BASELINE", "0") or 0)
    vs = throughput / baseline if baseline > 0 else 1.0
    print(json.dumps({
        "metric": "resnet50_synthetic_images_per_sec_per_chip",
        "value": round(throughput, 2),
        "unit": "images/sec",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
