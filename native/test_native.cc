// C-level assert harness for the native runtime — the tier the reference
// covers with gtest (test/singa/*.cc). Exercises the record-file and
// TCP-endpoint edge cases that ctypes-driven pytest cannot reach
// precisely: truncated records, bad magic, byte-dribbled partial frames,
// oversized-frame protocol violations, multi-megabyte short-read
// reassembly, ACK drains, and shutdown with blocked waiters.
//
// Plain asserts + main() (no gtest in the image); exits nonzero on the
// first failure. Built by `make -C native test` and driven from
// tests/test_native_harness.py.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cassert>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <string>
#include <thread>
#include <vector>

// C ABI of the two runtimes (mirrors singa_tpu/native/__init__.py /
// singa_tpu/network.py ctypes declarations)
extern "C" {
void* sg_recwriter_open(const char*, int);
int sg_recwriter_write(void*, const char*, uint32_t, const char*, uint32_t);
void sg_recwriter_flush(void*);
void sg_recwriter_close(void*);
void* sg_recreader_open(const char*, int);
int sg_recreader_read(void*, char**, uint32_t*, char**, uint32_t*);
int sg_recreader_count(const char*);
void sg_recreader_seek_to_first(void*);
void sg_recreader_close(void*);
void sg_free(void*);

void* sg_net_create(int);
int sg_net_port(void*);
void sg_net_shutdown(void*);
void sg_net_destroy(void*);
int64_t sg_net_connect(void*, const char*, int);
void sg_ep_close(void*, int64_t);
int64_t sg_net_accept_ep(void*, int);
int64_t sg_ep_send(void*, int64_t, const void*, uint64_t, const void*,
                   uint64_t);
int sg_ep_recv_wait(void*, int64_t, int, uint64_t*, uint64_t*);
int sg_ep_recv_copy(void*, int64_t, void*, uint64_t, void*, uint64_t);
int sg_ep_pending(void*, int64_t);
int sg_ep_drain(void*, int64_t, int);
int sg_ep_status(void*, int64_t);
}

#define CHECK(cond)                                                       \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,       \
                   __LINE__, #cond);                                      \
      std::exit(1);                                                       \
    }                                                                     \
  } while (0)

static std::string tmp_file(const char* stem) {
  const char* dir = std::getenv("TEST_TMPDIR");
  std::string p = dir ? dir : "/tmp";
  p += "/";
  p += stem;
  return p;
}

// ---------------------------------------------------------------------------
// record files
// ---------------------------------------------------------------------------

static void test_rec_roundtrip_with_nuls() {
  std::string path = tmp_file("rt.rec");
  void* w = sg_recwriter_open(path.c_str(), 0);
  CHECK(w);
  // keys/values containing NUL bytes must round-trip verbatim
  const char key[] = {'a', '\0', 'b'};
  const char val[] = {'\0', '\x7f', '\0', 'z'};
  CHECK(sg_recwriter_write(w, key, 3, val, 4) == 1);
  CHECK(sg_recwriter_write(w, "empty", 5, nullptr, 0) == 1);
  sg_recwriter_close(w);

  CHECK(sg_recreader_count(path.c_str()) == 2);
  void* r = sg_recreader_open(path.c_str(), 0);
  CHECK(r);
  char *k, *v;
  uint32_t kl, vl;
  CHECK(sg_recreader_read(r, &k, &kl, &v, &vl) == 1);
  CHECK(kl == 3 && std::memcmp(k, key, 3) == 0);
  CHECK(vl == 4 && std::memcmp(v, val, 4) == 0);
  sg_free(k);
  sg_free(v);
  CHECK(sg_recreader_read(r, &k, &kl, &v, &vl) == 1);
  CHECK(kl == 5 && vl == 0);
  sg_free(k);
  sg_free(v);
  CHECK(sg_recreader_read(r, &k, &kl, &v, &vl) == 0);  // EOF
  sg_recreader_close(r);
  std::puts("ok rec_roundtrip_with_nuls");
}

static void test_rec_truncated_value() {
  std::string path = tmp_file("trunc.rec");
  void* w = sg_recwriter_open(path.c_str(), 0);
  CHECK(sg_recwriter_write(w, "k1", 2, "valuevalue", 10) == 1);
  CHECK(sg_recwriter_write(w, "k2", 2, "xxxxxxxxxx", 10) == 1);
  sg_recwriter_close(w);

  // cut the file mid-way through the SECOND record's value
  std::ifstream in(path, std::ios::binary);
  std::string all((std::istreambuf_iterator<char>(in)),
                  std::istreambuf_iterator<char>());
  in.close();
  std::ofstream out(path, std::ios::binary | std::ios::trunc);
  out.write(all.data(), static_cast<long>(all.size() - 5));
  out.close();

  // the intact first record reads; the torn tail terminates cleanly
  CHECK(sg_recreader_count(path.c_str()) == 1);
  void* r = sg_recreader_open(path.c_str(), 0);
  char *k, *v;
  uint32_t kl, vl;
  CHECK(sg_recreader_read(r, &k, &kl, &v, &vl) == 1);
  CHECK(kl == 2 && std::memcmp(k, "k1", 2) == 0 && vl == 10);
  sg_free(k);
  sg_free(v);
  CHECK(sg_recreader_read(r, &k, &kl, &v, &vl) == 0);
  sg_recreader_close(r);
  std::puts("ok rec_truncated_value");
}

static void test_rec_bad_magic_and_short_header() {
  std::string path = tmp_file("bad.rec");
  std::ofstream out(path, std::ios::binary);
  out << "NOTMAGIC";
  out.close();
  CHECK(sg_recreader_open(path.c_str(), 0) == nullptr);
  CHECK(sg_recreader_count(path.c_str()) == -1);
  // shorter than the magic itself
  std::ofstream o2(path, std::ios::binary | std::ios::trunc);
  o2 << "SG";
  o2.close();
  CHECK(sg_recreader_open(path.c_str(), 0) == nullptr);
  std::puts("ok rec_bad_magic_and_short_header");
}

static void test_rec_append_and_prefetch_epochs() {
  std::string path = tmp_file("app.rec");
  void* w = sg_recwriter_open(path.c_str(), 0);
  for (int i = 0; i < 50; ++i) {
    std::string k = "k" + std::to_string(i);
    CHECK(sg_recwriter_write(w, k.c_str(),
                             static_cast<uint32_t>(k.size()), "v", 1) == 1);
  }
  sg_recwriter_close(w);
  w = sg_recwriter_open(path.c_str(), 1);  // append: NO second magic
  CHECK(sg_recwriter_write(w, "extra", 5, "v", 1) == 1);
  sg_recwriter_close(w);
  CHECK(sg_recreader_count(path.c_str()) == 51);

  // prefetching reader sees the same sequence, twice (epoch rewind)
  void* r = sg_recreader_open(path.c_str(), 4);
  for (int epoch = 0; epoch < 2; ++epoch) {
    char *k, *v;
    uint32_t kl, vl;
    int n = 0;
    std::string first;
    while (sg_recreader_read(r, &k, &kl, &v, &vl) == 1) {
      if (n == 0) first.assign(k, kl);
      ++n;
      sg_free(k);
      sg_free(v);
    }
    CHECK(n == 51);
    CHECK(first == "k0");
    sg_recreader_seek_to_first(r);
  }
  sg_recreader_close(r);
  std::puts("ok rec_append_and_prefetch_epochs");
}

static void test_rec_close_while_prefetching() {
  std::string path = tmp_file("close.rec");
  void* w = sg_recwriter_open(path.c_str(), 0);
  std::string big(1 << 16, 'x');
  for (int i = 0; i < 64; ++i)
    CHECK(sg_recwriter_write(w, "k", 1, big.data(),
                             static_cast<uint32_t>(big.size())) == 1);
  sg_recwriter_close(w);
  // close with the prefetch thread mid-file: must join, not hang/crash
  void* r = sg_recreader_open(path.c_str(), 2);
  char *k, *v;
  uint32_t kl, vl;
  CHECK(sg_recreader_read(r, &k, &kl, &v, &vl) == 1);
  sg_free(k);
  sg_free(v);
  sg_recreader_close(r);
  std::puts("ok rec_close_while_prefetching");
}

// ---------------------------------------------------------------------------
// TCP endpoints
// ---------------------------------------------------------------------------

static void test_net_roundtrip_and_ack() {
  void* srv = sg_net_create(0);
  CHECK(srv);
  int port = sg_net_port(srv);
  CHECK(port > 0);
  void* cli = sg_net_create(0);
  int64_t c = sg_net_connect(cli, "127.0.0.1", port);
  CHECK(c > 0);
  int64_t s = sg_net_accept_ep(srv, 2000);
  CHECK(s > 0);

  CHECK(sg_ep_send(cli, c, "meta", 4, "payload", 7) > 0);
  uint64_t ms = 0, ps = 0;
  CHECK(sg_ep_recv_wait(srv, s, 2000, &ms, &ps) == 1);
  CHECK(ms == 4 && ps == 7);
  std::vector<char> meta(ms), pay(ps);
  CHECK(sg_ep_recv_copy(srv, s, meta.data(), ms, pay.data(), ps) == 0);
  CHECK(std::memcmp(meta.data(), "meta", 4) == 0);
  CHECK(std::memcmp(pay.data(), "payload", 7) == 0);
  // the receive must have triggered an ACK back to the sender
  CHECK(sg_ep_drain(cli, c, 2000) == 1);
  CHECK(sg_ep_pending(cli, c) == 0);
  sg_net_destroy(cli);
  sg_net_destroy(srv);
  std::puts("ok net_roundtrip_and_ack");
}

static void test_net_partial_frames_dribbled() {
  // a DATA frame delivered one byte at a time across many TCP segments
  // must assemble identically (the poll-loop state machine's core claim)
  void* srv = sg_net_create(0);
  int port = sg_net_port(srv);

  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
  CHECK(::connect(fd, reinterpret_cast<sockaddr*>(&addr),
                  sizeof(addr)) == 0);
  int64_t s = sg_net_accept_ep(srv, 2000);
  CHECK(s > 0);

  // hand-build the frame: u8 type | u32 id | u64 msize | u64 psize
  std::string m = "mm", p = "ppp";
  std::string f;
  f.push_back(0);  // kMsgData
  uint32_t id = 9;
  uint64_t msz = m.size(), psz = p.size();
  f.append(reinterpret_cast<char*>(&id), 4);
  f.append(reinterpret_cast<char*>(&msz), 8);
  f.append(reinterpret_cast<char*>(&psz), 8);
  f += m;
  f += p;
  for (char ch : f) {
    CHECK(::send(fd, &ch, 1, 0) == 1);
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  uint64_t ms = 0, ps = 0;
  CHECK(sg_ep_recv_wait(srv, s, 3000, &ms, &ps) == 1);
  CHECK(ms == 2 && ps == 3);
  char mb[8] = {0}, pb[8] = {0};
  CHECK(sg_ep_recv_copy(srv, s, mb, sizeof(mb), pb, sizeof(pb)) == 0);
  CHECK(std::memcmp(mb, "mm", 2) == 0 && std::memcmp(pb, "ppp", 3) == 0);

  // half a header then a hard close: the server must stay alive and
  // keep serving fresh connections
  int fd2 = ::socket(AF_INET, SOCK_STREAM, 0);
  CHECK(::connect(fd2, reinterpret_cast<sockaddr*>(&addr),
                  sizeof(addr)) == 0);
  int64_t s2 = sg_net_accept_ep(srv, 2000);
  CHECK(s2 > 0);
  char half[7] = {0};
  CHECK(::send(fd2, half, sizeof(half), 0) == sizeof(half));
  ::close(fd2);
  std::this_thread::sleep_for(std::chrono::milliseconds(100));

  void* cli = sg_net_create(0);
  int64_t c = sg_net_connect(cli, "127.0.0.1", port);
  CHECK(c > 0);
  int64_t s3 = sg_net_accept_ep(srv, 2000);
  CHECK(s3 > 0);
  CHECK(sg_ep_send(cli, c, "x", 1, "y", 1) > 0);
  CHECK(sg_ep_recv_wait(srv, s3, 2000, &ms, &ps) == 1);
  sg_net_destroy(cli);
  ::close(fd);
  sg_net_destroy(srv);
  std::puts("ok net_partial_frames_dribbled");
}

static void test_net_large_payload_short_reads() {
  // multi-MB payload crosses the socket in many short reads; must
  // reassemble bit-exact
  void* srv = sg_net_create(0);
  int port = sg_net_port(srv);
  void* cli = sg_net_create(0);
  int64_t c = sg_net_connect(cli, "127.0.0.1", port);
  int64_t s = sg_net_accept_ep(srv, 2000);
  CHECK(c > 0 && s > 0);

  std::string big(5 * 1024 * 1024, 0);
  for (size_t i = 0; i < big.size(); ++i)
    big[i] = static_cast<char>((i * 131) & 0xff);
  CHECK(sg_ep_send(cli, c, "blob", 4, big.data(), big.size()) > 0);
  uint64_t ms = 0, ps = 0;
  CHECK(sg_ep_recv_wait(srv, s, 10000, &ms, &ps) == 1);
  CHECK(ps == big.size());
  std::vector<char> meta(ms);
  std::vector<char> pay(ps);
  CHECK(sg_ep_recv_copy(srv, s, meta.data(), ms, pay.data(), ps) == 0);
  CHECK(std::memcmp(pay.data(), big.data(), big.size()) == 0);
  CHECK(sg_ep_drain(cli, c, 5000) == 1);
  sg_net_destroy(cli);
  sg_net_destroy(srv);
  std::puts("ok net_large_payload_short_reads");
}

static void test_net_recv_timeout_and_shutdown_wakes_waiter() {
  void* srv = sg_net_create(0);
  int port = sg_net_port(srv);
  void* cli = sg_net_create(0);
  int64_t c = sg_net_connect(cli, "127.0.0.1", port);
  int64_t s = sg_net_accept_ep(srv, 2000);
  CHECK(c > 0 && s > 0);

  uint64_t ms, ps;
  auto t0 = std::chrono::steady_clock::now();
  CHECK(sg_ep_recv_wait(srv, s, 100, &ms, &ps) == 0);  // idle: timeout
  auto dt = std::chrono::steady_clock::now() - t0;
  CHECK(dt >= std::chrono::milliseconds(90));

  // a waiter blocked in a LONG recv is woken promptly by shutdown
  std::thread waiter([&] {
    uint64_t m2, p2;
    sg_ep_recv_wait(srv, s, 60000, &m2, &p2);
  });
  std::this_thread::sleep_for(std::chrono::milliseconds(50));
  t0 = std::chrono::steady_clock::now();
  sg_net_shutdown(srv);
  waiter.join();
  dt = std::chrono::steady_clock::now() - t0;
  CHECK(dt < std::chrono::seconds(5));
  sg_net_destroy(srv);
  sg_net_destroy(cli);
  std::puts("ok net_recv_timeout_and_shutdown_wakes_waiter");
}

static void test_net_oversized_frame_drops_connection() {
  // a frame claiming a > 1 GiB body is a protocol violation: the server
  // must drop that connection (not allocate), and stay healthy
  void* srv = sg_net_create(0);
  int port = sg_net_port(srv);
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
  CHECK(::connect(fd, reinterpret_cast<sockaddr*>(&addr),
                  sizeof(addr)) == 0);
  int64_t s = sg_net_accept_ep(srv, 2000);
  CHECK(s > 0);
  std::string f;
  f.push_back(0);
  uint32_t id = 1;
  uint64_t msz = (2ull << 30), psz = 0;   // 2 GiB meta claim
  f.append(reinterpret_cast<char*>(&id), 4);
  f.append(reinterpret_cast<char*>(&msz), 8);
  f.append(reinterpret_cast<char*>(&psz), 8);
  CHECK(::send(fd, f.data(), f.size(), 0) ==
        static_cast<long>(f.size()));
  // endpoint goes to error state (3) within the poll loop's next beats
  int tries = 0;
  while (sg_ep_status(srv, s) != 3 && tries++ < 100)
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  CHECK(sg_ep_status(srv, s) == 3);
  ::close(fd);
  sg_net_destroy(srv);
  std::puts("ok net_oversized_frame_drops_connection");
}

int main() {
  test_rec_roundtrip_with_nuls();
  test_rec_truncated_value();
  test_rec_bad_magic_and_short_header();
  test_rec_append_and_prefetch_epochs();
  test_rec_close_while_prefetching();
  test_net_roundtrip_and_ack();
  test_net_partial_frames_dribbled();
  test_net_large_payload_short_reads();
  test_net_recv_timeout_and_shutdown_wakes_waiter();
  test_net_oversized_frame_drops_connection();
  std::puts("ALL NATIVE TESTS PASSED");
  return 0;
}
