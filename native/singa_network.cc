// TCP message-passing layer: the tpu-native peer of the reference's
// EndPoint network (include/singa/io/network.h:62-136,
// src/io/network/endpoint.cc) — a control-plane side channel for
// multi-host deployments (the data plane is XLA collectives over ICI/DCN).
//
// Design differences from the reference (which uses libev): one background
// thread multiplexes every connection with poll(2); messages are framed as
//   u8 type | u32 id | u64 msize | u64 psize | meta bytes | payload bytes
// DATA messages are acknowledged with an ACK frame carrying the same id so
// senders can await delivery (sg_ep_pending); C ABI for ctypes binding.

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#define SG_EXPORT extern "C" __attribute__((visibility("default")))

namespace {

constexpr uint8_t kMsgData = 0;
constexpr uint8_t kMsgAck = 1;
constexpr size_t kHeaderSize = 1 + 4 + 8 + 8;

enum ConnStatus { kConnInit = 0, kConnPending = 1, kConnEst = 2,
                  kConnError = 3 };

struct Msg {
  uint8_t type = kMsgData;
  uint32_t id = 0;
  std::string meta, payload;
};

std::string frame(const Msg& m) {
  std::string out;
  out.reserve(kHeaderSize + m.meta.size() + m.payload.size());
  out.push_back(static_cast<char>(m.type));
  uint32_t id = m.id;
  uint64_t ms = m.meta.size(), ps = m.payload.size();
  out.append(reinterpret_cast<char*>(&id), 4);
  out.append(reinterpret_cast<char*>(&ms), 8);
  out.append(reinterpret_cast<char*>(&ps), 8);
  out += m.meta;
  out += m.payload;
  return out;
}

// A peer claiming a single frame larger than this is treated as a protocol
// violation (malformed/hostile client) and its connection is dropped — the
// sizes come off the wire and must never drive an allocation unchecked.
constexpr uint64_t kMaxFrameBody = 1ull << 30;  // 1 GiB each for meta/payload

struct EndPoint {
  int fd = -1;
  int status = kConnInit;
  uint32_t next_id = 1;
  int pending_acks = 0;            // sent DATA frames not yet ACKed
  int waiters = 0;                 // threads blocked on cv right now
  std::deque<std::string> sendq;   // framed bytes awaiting the socket
  size_t send_off = 0;             // offset into sendq.front()
  std::deque<Msg> recvq;
  std::condition_variable cv;
  // wire-read state machine
  std::string rbuf;
  // identity for diagnostics
  std::string peer;
};

struct Net {
  int listen_fd = -1;
  int port = 0;
  int wake[2] = {-1, -1};
  std::thread thr;
  std::atomic<bool> stop{false};
  bool closing = false;            // guarded by mtx; wakes blocked waiters
  std::mutex mtx;                  // guards eps, new_eps, every EndPoint
  std::map<int64_t, EndPoint*> eps;
  std::vector<EndPoint*> graveyard;  // closed endpoints; freed in ~Net so
                                     // woken waiters never touch freed mem
  std::deque<int64_t> new_eps;     // inbound endpoints not yet claimed
  std::condition_variable new_cv;
  int64_t next_handle = 1;

  ~Net() {
    for (auto& kv : eps) {
      if (kv.second->fd >= 0) ::close(kv.second->fd);
      delete kv.second;
    }
    for (auto* ep : graveyard) delete ep;
    if (listen_fd >= 0) ::close(listen_fd);
    if (wake[0] >= 0) ::close(wake[0]);
    if (wake[1] >= 0) ::close(wake[1]);
  }

  void poke() { char c = 1; (void)!::write(wake[1], &c, 1); }
};

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

void set_nodelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

void mark_error(EndPoint* ep);

// Parse as many complete frames out of ep->rbuf as possible.
// DATA frames go to recvq (and enqueue an ACK); ACK frames decrement
// pending_acks. Parsing advances an offset and compacts the buffer once
// at the end (erasing per-frame would be O(n^2) across a burst of small
// frames). Caller holds net->mtx.
void drain_frames(Net* net, EndPoint* ep) {
  size_t off = 0;
  for (;;) {
    if (ep->rbuf.size() - off < kHeaderSize) break;
    const char* p = ep->rbuf.data() + off;
    uint8_t type = static_cast<uint8_t>(p[0]);
    uint32_t id;
    uint64_t ms, ps;
    std::memcpy(&id, p + 1, 4);
    std::memcpy(&ms, p + 5, 8);
    std::memcpy(&ps, p + 13, 8);
    if (type != kMsgData && type != kMsgAck) {
      // not our protocol (e.g. a stray HTTP client) — drop immediately
      // instead of buffering garbage while waiting for a bogus frame
      mark_error(ep);
      return;
    }
    if (ms > kMaxFrameBody || ps > kMaxFrameBody) {
      // hostile or corrupt frame: sizes would wrap/overallocate
      mark_error(ep);
      return;
    }
    size_t total = kHeaderSize + static_cast<size_t>(ms) +
                   static_cast<size_t>(ps);
    if (ep->rbuf.size() - off < total) break;
    if (type == kMsgAck) {
      if (ep->pending_acks > 0) --ep->pending_acks;
      ep->cv.notify_all();
    } else {
      Msg m;
      m.type = type;
      m.id = id;
      m.meta.assign(p + kHeaderSize, ms);
      m.payload.assign(p + kHeaderSize + ms, ps);
      ep->recvq.push_back(std::move(m));
      Msg ack;
      ack.type = kMsgAck;
      ack.id = id;
      ep->sendq.push_back(frame(ack));
      ep->cv.notify_all();
    }
    off += total;
  }
  if (off > 0) ep->rbuf.erase(0, off);
}

void mark_error(EndPoint* ep) {
  if (ep->fd >= 0) ::close(ep->fd);
  ep->fd = -1;
  ep->status = kConnError;
  ep->cv.notify_all();
}

// Free retired endpoints nobody can reach anymore. ONLY the io thread may
// call this, at the top of its loop BEFORE rebuilding its pollfd snapshot:
// that snapshot holds raw EndPoint* across the (unlocked) poll() window,
// so endpoints retired mid-iteration must survive until the next rebuild.
// Handles already erased from net->eps can gain no new cv waiters —
// lookups fail — so waiters == 0 there means unreachable. Caller holds
// net->mtx.
void reap_graveyard(Net* net) {
  auto& g = net->graveyard;
  for (size_t i = 0; i < g.size();) {
    if (g[i]->waiters == 0) {
      delete g[i];
      g[i] = g.back();
      g.pop_back();
    } else {
      ++i;
    }
  }
}

void io_loop(Net* net) {
  std::vector<pollfd> pfds;
  std::vector<EndPoint*> pfd_eps;
  char buf[1 << 16];
  for (;;) {
    if (net->stop.load()) return;
    pfds.clear();
    pfd_eps.clear();
    pfds.push_back({net->wake[0], POLLIN, 0});
    pfd_eps.push_back(nullptr);
    if (net->listen_fd >= 0) {
      pfds.push_back({net->listen_fd, POLLIN, 0});
      pfd_eps.push_back(nullptr);
    }
    {
      std::lock_guard<std::mutex> lk(net->mtx);
      reap_graveyard(net);
      for (auto& kv : net->eps) {
        EndPoint* ep = kv.second;
        if (ep->fd < 0) continue;
        short ev = POLLIN;
        if (!ep->sendq.empty() || ep->status == kConnPending) ev |= POLLOUT;
        pfds.push_back({ep->fd, ev, 0});
        pfd_eps.push_back(ep);
      }
    }
    int rc = ::poll(pfds.data(), pfds.size(), 200);
    if (rc < 0 && errno != EINTR) return;
    if (net->stop.load()) return;
    for (size_t i = 0; i < pfds.size(); ++i) {
      if (!pfds[i].revents) continue;
      if (pfds[i].fd == net->wake[0]) {
        (void)!::read(net->wake[0], buf, sizeof(buf));
        continue;
      }
      if (net->listen_fd >= 0 && pfds[i].fd == net->listen_fd) {
        sockaddr_in cli{};
        socklen_t len = sizeof(cli);
        int cfd = ::accept(net->listen_fd,
                           reinterpret_cast<sockaddr*>(&cli), &len);
        if (cfd >= 0) {
          set_nonblock(cfd);
          set_nodelay(cfd);
          auto* ep = new EndPoint();
          ep->fd = cfd;
          ep->status = kConnEst;
          char ipbuf[64];
          inet_ntop(AF_INET, &cli.sin_addr, ipbuf, sizeof(ipbuf));
          ep->peer = std::string(ipbuf) + ":" +
                     std::to_string(ntohs(cli.sin_port));
          std::lock_guard<std::mutex> lk(net->mtx);
          int64_t h = net->next_handle++;
          net->eps[h] = ep;
          net->new_eps.push_back(h);
          net->new_cv.notify_all();
        }
        continue;
      }
      EndPoint* ep = pfd_eps[i];
      if (!ep) continue;
      std::lock_guard<std::mutex> lk(net->mtx);
      if (ep->fd < 0) continue;
      if (pfds[i].revents & (POLLERR | POLLHUP | POLLNVAL)) {
        // flush whatever was readable before the peer closed
        ssize_t n;
        while ((n = ::read(ep->fd, buf, sizeof(buf))) > 0)
          ep->rbuf.append(buf, n);
        drain_frames(net, ep);
        mark_error(ep);
        continue;
      }
      if (ep->status == kConnPending && (pfds[i].revents & POLLOUT)) {
        int err = 0;
        socklen_t elen = sizeof(err);
        getsockopt(ep->fd, SOL_SOCKET, SO_ERROR, &err, &elen);
        if (err != 0) {
          mark_error(ep);
          continue;
        }
        ep->status = kConnEst;
        ep->cv.notify_all();
      }
      if (pfds[i].revents & POLLIN) {
        ssize_t n;
        bool closed = false;
        while ((n = ::read(ep->fd, buf, sizeof(buf))) > 0)
          ep->rbuf.append(buf, n);
        if (n == 0) closed = true;
        if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) closed = true;
        drain_frames(net, ep);
        if (closed) {
          mark_error(ep);
          continue;
        }
      }
      if ((pfds[i].revents & POLLOUT) && ep->status == kConnEst) {
        while (!ep->sendq.empty()) {
          const std::string& front = ep->sendq.front();
          ssize_t n = ::write(ep->fd, front.data() + ep->send_off,
                              front.size() - ep->send_off);
          if (n < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK) break;
            mark_error(ep);
            break;
          }
          ep->send_off += n;
          if (ep->send_off == front.size()) {
            ep->sendq.pop_front();
            ep->send_off = 0;
          }
        }
      }
    }
  }
}

}  // namespace

SG_EXPORT void* sg_net_create(int port) {
  auto* net = new Net();
  if (::pipe(net->wake) != 0) {
    delete net;
    return nullptr;
  }
  set_nonblock(net->wake[0]);
  if (port >= 0) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
        ::listen(fd, 64) != 0) {
      ::close(fd);
      delete net;
      return nullptr;
    }
    socklen_t alen = sizeof(addr);
    getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    net->port = ntohs(addr.sin_port);
    set_nonblock(fd);
    net->listen_fd = fd;
  }
  net->thr = std::thread(io_loop, net);
  return net;
}

SG_EXPORT int sg_net_port(void* h) {
  return static_cast<Net*>(h)->port;
}

// Begin teardown WITHOUT freeing: refuse new waits and wake every blocked
// recv/drain/connect/accept so in-flight callers unwind. The Python layer
// calls this, waits for its in-flight count to hit zero, then calls
// sg_net_destroy — which makes the free race-proof without the C layer
// needing handle refcounts.
SG_EXPORT void sg_net_shutdown(void* h) {
  auto* net = static_cast<Net*>(h);
  std::lock_guard<std::mutex> lk(net->mtx);
  net->closing = true;
  net->new_cv.notify_all();
  for (auto& kv : net->eps) kv.second->cv.notify_all();
  for (auto* ep : net->graveyard) ep->cv.notify_all();
}

SG_EXPORT void sg_net_destroy(void* h) {
  auto* net = static_cast<Net*>(h);
  {
    // wake every blocked recv/drain/connect/accept and wait for them to
    // leave before tearing the Net down (no use-after-free on close race)
    std::unique_lock<std::mutex> lk(net->mtx);
    net->closing = true;
    net->new_cv.notify_all();
    for (auto& kv : net->eps) kv.second->cv.notify_all();
    // wait until every waiter has left: the closing flag is part of
    // each wait predicate, so the notify above wakes them all — but a
    // consumer mid-recv with a long timeout may take a scheduling beat
    // to observe it, and deleting the Net from under a live waiter is a
    // use-after-free. A waiter that never leaves (a wedged consumer
    // thread, or a native caller sitting in a long recv timeout without
    // the Python layer's 200ms slicing) must not turn close() into an
    // unbounded hang either: after a generous deadline we log loudly
    // and LEAK the Net — bounded shutdown, and the UAF stays ruled out
    // because the memory stays valid for the stuck waiter.
    const auto deadline =
        std::chrono::steady_clock::now() + std::chrono::seconds(30);
    for (;;) {
      bool busy = false;
      for (auto& kv : net->eps)
        if (kv.second->waiters > 0) busy = true;
      for (auto* ep : net->graveyard)
        if (ep->waiters > 0) busy = true;
      if (!busy) break;
      if (std::chrono::steady_clock::now() > deadline) {
        std::fprintf(stderr,
                     "[singa_network] sg_net_destroy: waiter still "
                     "blocked after 30s; leaking Net %p instead of "
                     "freeing under a live waiter\n", h);
        std::fflush(stderr);
        net->stop.store(true);
        net->poke();
        return;  // threads + fds leak with the Net; process exit reaps
      }
      lk.unlock();
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
      lk.lock();
      net->new_cv.notify_all();
      for (auto& kv : net->eps) kv.second->cv.notify_all();
      for (auto* ep : net->graveyard) ep->cv.notify_all();
    }
  }
  net->stop.store(true);
  net->poke();
  if (net->thr.joinable()) net->thr.join();
  delete net;
}

// Connect to host:port. The connect is NON-blocking — the io thread
// completes it via the kConnPending -> POLLOUT -> SO_ERROR path — and this
// call waits (with retries, reference MAX_RETRY_CNT) for establishment.
// Returns an endpoint handle > 0, or 0 on failure.
SG_EXPORT int64_t sg_net_connect(void* h, const char* host, int port) {
  auto* net = static_cast<Net*>(h);
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  std::string ports = std::to_string(port);
  if (getaddrinfo(host, ports.c_str(), &hints, &res) != 0 || !res) return 0;
  int64_t handle = 0;
  for (int attempt = 0; attempt < 3 && handle == 0; ++attempt) {
    if (attempt > 0)
      std::this_thread::sleep_for(std::chrono::milliseconds(50 << attempt));
    // walk every resolved address each attempt (multi-homed hosts)
    bool stop = false;
    for (addrinfo* ai = res; ai && handle == 0 && !stop; ai = ai->ai_next) {
      int fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd < 0) continue;
      set_nonblock(fd);
      set_nodelay(fd);
      int rc = ::connect(fd, ai->ai_addr, ai->ai_addrlen);
      if (rc != 0 && errno != EINPROGRESS) {
        ::close(fd);
        continue;
      }
      auto* ep = new EndPoint();
      ep->fd = fd;
      ep->status = rc == 0 ? kConnEst : kConnPending;
      ep->peer = std::string(host) + ":" + std::to_string(port);
      std::unique_lock<std::mutex> lk(net->mtx);
      int64_t cand = net->next_handle++;
      net->eps[cand] = ep;
      net->poke();
      // wait for the io thread to finish the handshake
      ++ep->waiters;
      ep->cv.wait_for(lk, std::chrono::seconds(5), [&] {
        return ep->status != kConnPending || net->closing;
      });
      --ep->waiters;
      if (ep->status == kConnEst) {
        handle = cand;
      } else {
        // failed address: retire the endpoint, try the next one
        if (ep->fd >= 0) ::close(ep->fd);
        ep->fd = -1;
        ep->status = kConnError;
        net->eps.erase(cand);
        net->graveyard.push_back(ep);
        if (net->closing) stop = true;
      }
    }
    if (stop) break;
  }
  freeaddrinfo(res);
  return handle;
}

// Close one endpoint: drop its socket and queues and retire it. Any thread
// blocked in recv/drain wakes with an error. The EndPoint struct itself is
// kept on a graveyard until sg_net_destroy so waiters never race a free.
SG_EXPORT void sg_ep_close(void* h, int64_t ep_h) {
  auto* net = static_cast<Net*>(h);
  std::lock_guard<std::mutex> lk(net->mtx);
  auto it = net->eps.find(ep_h);
  if (it == net->eps.end()) return;
  EndPoint* ep = it->second;
  mark_error(ep);
  ep->sendq.clear();
  ep->recvq.clear();
  ep->rbuf.clear();
  ep->rbuf.shrink_to_fit();
  net->eps.erase(it);
  net->graveyard.push_back(ep);
  net->poke();                    // io thread reaps on its next rebuild
}

// Claim the next inbound endpoint (created by a peer's connect), waiting
// up to timeout_ms. Returns 0 on timeout. (reference
// EndPointFactory::getNewEps)
SG_EXPORT int64_t sg_net_accept_ep(void* h, int timeout_ms) {
  auto* net = static_cast<Net*>(h);
  std::unique_lock<std::mutex> lk(net->mtx);
  if (!net->new_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                            [&] {
                              return !net->new_eps.empty() || net->closing;
                            }) ||
      net->new_eps.empty())
    return 0;
  int64_t handle = net->new_eps.front();
  net->new_eps.pop_front();
  return handle;
}

// Queue a message for sending; returns its id (>0), or -1 when the
// endpoint is in error state.
SG_EXPORT int64_t sg_ep_send(void* h, int64_t ep_h, const void* meta,
                             uint64_t msize, const void* payload,
                             uint64_t psize) {
  auto* net = static_cast<Net*>(h);
  std::lock_guard<std::mutex> lk(net->mtx);
  auto it = net->eps.find(ep_h);
  if (it == net->eps.end()) return -1;
  EndPoint* ep = it->second;
  if (ep->status == kConnError) return -1;
  Msg m;
  m.type = kMsgData;
  m.id = ep->next_id++;
  if (meta && msize) m.meta.assign(static_cast<const char*>(meta), msize);
  if (payload && psize)
    m.payload.assign(static_cast<const char*>(payload), psize);
  ep->sendq.push_back(frame(m));
  ++ep->pending_acks;
  net->poke();
  return m.id;
}

// Blocking receive with timeout. On success fills sizes and returns 1 and
// the caller then copies out via sg_ep_recv_copy; returns 0 on timeout,
// -1 when the endpoint errored and its queue is empty. The wait/copy pair
// is not atomic — concurrent receivers on ONE endpoint must serialize
// (the Python EndPoint wrapper holds a per-endpoint lock across the pair;
// recv_copy additionally truncates to the caller's capacities so a racy
// caller can never overflow its buffers).
SG_EXPORT int sg_ep_recv_wait(void* h, int64_t ep_h, int timeout_ms,
                              uint64_t* msize, uint64_t* psize) {
  auto* net = static_cast<Net*>(h);
  std::unique_lock<std::mutex> lk(net->mtx);
  auto it = net->eps.find(ep_h);
  if (it == net->eps.end()) return -1;
  EndPoint* ep = it->second;
  ++ep->waiters;
  bool got = ep->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
    return !ep->recvq.empty() || ep->status == kConnError || net->closing;
  });
  --ep->waiters;
  if (!got) return 0;
  if (ep->recvq.empty()) return -1;
  *msize = ep->recvq.front().meta.size();
  *psize = ep->recvq.front().payload.size();
  return 1;
}

SG_EXPORT int sg_ep_recv_copy(void* h, int64_t ep_h, void* meta,
                              uint64_t meta_cap, void* payload,
                              uint64_t payload_cap) {
  auto* net = static_cast<Net*>(h);
  std::lock_guard<std::mutex> lk(net->mtx);
  auto it = net->eps.find(ep_h);
  if (it == net->eps.end() || it->second->recvq.empty()) return -1;
  Msg& m = it->second->recvq.front();
  if (meta && !m.meta.empty())
    std::memcpy(meta, m.meta.data(),
                m.meta.size() < meta_cap ? m.meta.size() : meta_cap);
  if (payload && !m.payload.empty())
    std::memcpy(payload, m.payload.data(),
                m.payload.size() < payload_cap ? m.payload.size()
                                               : payload_cap);
  int truncated = (m.meta.size() > meta_cap ||
                   m.payload.size() > payload_cap) ? 1 : 0;
  it->second->recvq.pop_front();
  return truncated;
}

// DATA frames sent on this endpoint not yet acknowledged by the peer.
SG_EXPORT int sg_ep_pending(void* h, int64_t ep_h) {
  auto* net = static_cast<Net*>(h);
  std::lock_guard<std::mutex> lk(net->mtx);
  auto it = net->eps.find(ep_h);
  if (it == net->eps.end()) return -1;
  return it->second->pending_acks;
}

// Wait until every sent DATA frame has been ACKed (or timeout/error).
// Returns 1 on fully-acked, 0 on timeout, -1 on error.
SG_EXPORT int sg_ep_drain(void* h, int64_t ep_h, int timeout_ms) {
  auto* net = static_cast<Net*>(h);
  std::unique_lock<std::mutex> lk(net->mtx);
  auto it = net->eps.find(ep_h);
  if (it == net->eps.end()) return -1;
  EndPoint* ep = it->second;
  ++ep->waiters;
  bool ok = ep->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
    return ep->pending_acks == 0 || ep->status == kConnError ||
           net->closing;
  });
  --ep->waiters;
  if (!ok) return 0;
  return ep->status == kConnError ? -1
         : ep->pending_acks == 0  ? 1
                                  : 0;
}

SG_EXPORT int sg_ep_status(void* h, int64_t ep_h) {
  auto* net = static_cast<Net*>(h);
  std::lock_guard<std::mutex> lk(net->mtx);
  auto it = net->eps.find(ep_h);
  if (it == net->eps.end()) return kConnError;
  return it->second->status;
}

SG_EXPORT int sg_ep_peer(void* h, int64_t ep_h, char* out, int cap) {
  auto* net = static_cast<Net*>(h);
  std::lock_guard<std::mutex> lk(net->mtx);
  auto it = net->eps.find(ep_h);
  if (it == net->eps.end()) return -1;
  const std::string& p = it->second->peer;
  int n = static_cast<int>(p.size()) < cap - 1
              ? static_cast<int>(p.size()) : cap - 1;
  std::memcpy(out, p.data(), n);
  out[n] = 0;
  return n;
}
