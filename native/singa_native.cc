// Native IO runtime for the TPU-native framework.
//
// Capability parity with the reference's C++ IO stack:
//  - record-file reader/writer  (reference src/io/binfile_{reader,writer}.cc:
//    magic-word delimited key/value records with a fixed-size read buffer)
//  - threaded prefetching reader (reference include/singa/utils/safe_queue.h
//    + the python-side prefetch pipeline, python/singa/data.py:60-124)
//  - image transforms: bilinear resize / crop / horizontal flip
//    (reference src/io/image_transformer.cc)
//  - leveled logging with a registered sink
//    (reference include/singa/utils/logging.h, channel.h)
//  - monotonic timer (reference include/singa/utils/timer.h)
//
// Exposed as a C ABI consumed from python via ctypes (replacing the
// reference's SWIG binding layer).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fstream>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#define SG_EXPORT extern "C" __attribute__((visibility("default")))

namespace {

// ---------------------------------------------------------------------------
// logging
// ---------------------------------------------------------------------------

typedef void (*sg_log_sink)(int severity, const char* msg);
std::atomic<sg_log_sink> g_log_sink{nullptr};
std::atomic<int> g_log_level{1};  // 0=DEBUG 1=INFO 2=WARNING 3=ERROR

void log_msg(int severity, const std::string& msg) {
  if (severity < g_log_level.load()) return;
  sg_log_sink sink = g_log_sink.load();
  if (sink) {
    sink(severity, msg.c_str());
  } else {
    static const char* names[] = {"DEBUG", "INFO", "WARNING", "ERROR"};
    int idx = severity < 0 ? 0 : (severity > 3 ? 3 : severity);
    std::fprintf(stderr, "[singa_native %s] %s\n", names[idx], msg.c_str());
  }
}

// ---------------------------------------------------------------------------
// record file format
//   header:  8-byte magic "SGTPREC0"
//   record:  u32 key_len, key bytes, u32 val_len, val bytes   (little endian)
// ---------------------------------------------------------------------------

constexpr char kMagic[8] = {'S', 'G', 'T', 'P', 'R', 'E', 'C', '0'};

struct RecordWriter {
  std::ofstream out;
};

struct Record {
  std::string key;
  std::string val;
};

// Bounded blocking queue (reference SafeQueue, include/singa/utils/
// safe_queue.h) used by the prefetching reader.
class BoundedQueue {
 public:
  explicit BoundedQueue(size_t cap) : cap_(cap) {}

  // Returns false once the queue is closed so producers stop promptly
  // (a close mid-file must not force a scan to EOF).
  bool push(Record&& r) {
    std::unique_lock<std::mutex> lk(mu_);
    not_full_.wait(lk, [&] { return q_.size() < cap_ || closed_; });
    if (closed_) return false;
    q_.push_back(std::move(r));
    not_empty_.notify_one();
    return true;
  }

  bool pop(Record* r) {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [&] { return !q_.empty() || done_ || closed_; });
    if (q_.empty()) return false;
    *r = std::move(q_.front());
    q_.pop_front();
    not_full_.notify_one();
    return true;
  }

  void set_done() {
    std::lock_guard<std::mutex> lk(mu_);
    done_ = true;
    not_empty_.notify_all();
  }

  void close() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

 private:
  size_t cap_;
  std::deque<Record> q_;
  std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
  bool done_ = false;
  bool closed_ = false;
};

struct RecordReader {
  std::ifstream in;
  std::string path;
  int prefetch_depth = 0;
  // prefetch machinery (nullptr when prefetch is off)
  std::unique_ptr<BoundedQueue> queue;
  std::thread worker;
  bool prefetching = false;

  ~RecordReader() { stop(); }

  void stop() {
    if (prefetching) {
      queue->close();
      if (worker.joinable()) worker.join();
      prefetching = false;
    }
  }

  void start_prefetch();
};

bool read_u32(std::ifstream& in, uint32_t* v) {
  char buf[4];
  if (!in.read(buf, 4)) return false;
  std::memcpy(v, buf, 4);
  return true;
}

bool read_record(std::ifstream& in, Record* r) {
  uint32_t klen;
  if (!read_u32(in, &klen)) return false;
  r->key.resize(klen);
  if (klen && !in.read(&r->key[0], klen)) return false;
  uint32_t vlen;
  if (!read_u32(in, &vlen)) return false;
  r->val.resize(vlen);
  if (vlen && !in.read(&r->val[0], vlen)) return false;
  return true;
}

void RecordReader::start_prefetch() {
  queue.reset(new BoundedQueue(static_cast<size_t>(prefetch_depth)));
  prefetching = true;
  RecordReader* r = this;
  worker = std::thread([r] {
    Record rec;
    while (read_record(r->in, &rec)) {
      if (!r->queue->push(std::move(rec))) break;
    }
    r->queue->set_done();
  });
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI: logging / timer
// ---------------------------------------------------------------------------

SG_EXPORT void sg_set_log_sink(sg_log_sink sink) { g_log_sink.store(sink); }

SG_EXPORT void sg_set_log_level(int level) { g_log_level.store(level); }

SG_EXPORT void sg_log(int severity, const char* msg) {
  log_msg(severity, msg ? msg : "");
}

SG_EXPORT double sg_monotonic_seconds() {
  auto now = std::chrono::steady_clock::now().time_since_epoch();
  return std::chrono::duration<double>(now).count();
}

// ---------------------------------------------------------------------------
// C ABI: named log channels (reference include/singa/utils/channel.h:35-77,
// src/utils/channel.cc) — append metric/progress lines to a per-channel
// file (default: <dir>/<name>) and/or stderr.
// ---------------------------------------------------------------------------

namespace {

struct LogChannel {
  std::string name;
  bool to_stderr = false;
  bool to_file = true;
  std::ofstream os;
  std::mutex mu;
};

struct ChannelManager {
  std::mutex mu;
  std::string dir;
  std::map<std::string, LogChannel*> chans;
};

ChannelManager& channel_manager() {
  static ChannelManager mgr;
  return mgr;
}

void channel_open_file(LogChannel* ch, const std::string& path) {
  if (ch->os.is_open()) ch->os.close();
  {
    std::ifstream fin(path.c_str());
    if (fin.good())
      log_msg(2, "channel messages will be appended to existing file: " +
                     path);
  }
  ch->os.open(path.c_str(), std::ios::app);
  if (!ch->os.is_open())
    log_msg(2, "cannot open channel file: " + path);
}

}  // namespace

SG_EXPORT void sg_set_channel_directory(const char* dir) {
  ChannelManager& mgr = channel_manager();
  std::lock_guard<std::mutex> lk(mgr.mu);
  mgr.dir = dir ? dir : "";
  if (!mgr.dir.empty() && mgr.dir.back() != '/') mgr.dir += '/';
}

SG_EXPORT void* sg_channel_get(const char* name) {
  ChannelManager& mgr = channel_manager();
  std::lock_guard<std::mutex> lk(mgr.mu);
  std::string nm = name ? name : "";
  auto it = mgr.chans.find(nm);
  if (it != mgr.chans.end()) return it->second;
  auto* ch = new LogChannel();
  ch->name = nm;
  channel_open_file(ch, mgr.dir + nm);
  mgr.chans[nm] = ch;
  return ch;
}

SG_EXPORT void sg_channel_enable_stderr(void* handle, int enable) {
  static_cast<LogChannel*>(handle)->to_stderr = enable != 0;
}

SG_EXPORT void sg_channel_enable_file(void* handle, int enable) {
  static_cast<LogChannel*>(handle)->to_file = enable != 0;
}

SG_EXPORT void sg_channel_set_dest_file(void* handle, const char* path) {
  auto* ch = static_cast<LogChannel*>(handle);
  std::lock_guard<std::mutex> lk(ch->mu);
  channel_open_file(ch, path ? path : "");
}

SG_EXPORT void sg_channel_send(void* handle, const char* msg) {
  auto* ch = static_cast<LogChannel*>(handle);
  std::lock_guard<std::mutex> lk(ch->mu);
  std::string m = msg ? msg : "";
  if (ch->to_stderr) std::fprintf(stderr, "%s\n", m.c_str());
  if (ch->to_file && ch->os.is_open()) {
    ch->os << m << "\n";
    ch->os.flush();
  }
}

// ---------------------------------------------------------------------------
// C ABI: record writer
// ---------------------------------------------------------------------------

SG_EXPORT void* sg_recwriter_open(const char* path, int append) {
  auto* w = new RecordWriter();
  auto mode = std::ios::binary | (append ? std::ios::app : std::ios::trunc);
  w->out.open(path, mode);
  if (!w->out.is_open()) {
    log_msg(3, std::string("cannot open for write: ") + path);
    delete w;
    return nullptr;
  }
  if (!append || w->out.tellp() == 0) w->out.write(kMagic, sizeof(kMagic));
  return w;
}

SG_EXPORT int sg_recwriter_write(void* handle, const char* key, uint32_t klen,
                                 const char* val, uint32_t vlen) {
  auto* w = static_cast<RecordWriter*>(handle);
  w->out.write(reinterpret_cast<const char*>(&klen), 4);
  if (klen) w->out.write(key, klen);
  w->out.write(reinterpret_cast<const char*>(&vlen), 4);
  if (vlen) w->out.write(val, vlen);
  return w->out.good() ? 1 : 0;
}

SG_EXPORT void sg_recwriter_flush(void* handle) {
  static_cast<RecordWriter*>(handle)->out.flush();
}

SG_EXPORT void sg_recwriter_close(void* handle) {
  auto* w = static_cast<RecordWriter*>(handle);
  w->out.close();
  delete w;
}

// ---------------------------------------------------------------------------
// C ABI: record reader (optionally with a background prefetch thread)
// ---------------------------------------------------------------------------

SG_EXPORT void* sg_recreader_open(const char* path, int prefetch_depth) {
  auto* r = new RecordReader();
  r->path = path;
  r->in.open(path, std::ios::binary);
  if (!r->in.is_open()) {
    log_msg(3, std::string("cannot open for read: ") + path);
    delete r;
    return nullptr;
  }
  char magic[8];
  if (!r->in.read(magic, 8) || std::memcmp(magic, kMagic, 8) != 0) {
    log_msg(3, std::string("bad record-file magic in ") + path);
    delete r;
    return nullptr;
  }
  r->prefetch_depth = prefetch_depth;
  if (prefetch_depth > 0) r->start_prefetch();
  return r;
}

// Returns 1 and fills key/val (malloc'd; caller frees with sg_free) or 0 at
// end of file.
SG_EXPORT int sg_recreader_read(void* handle, char** key, uint32_t* klen,
                                char** val, uint32_t* vlen) {
  auto* r = static_cast<RecordReader*>(handle);
  Record rec;
  bool ok = r->prefetching ? r->queue->pop(&rec) : read_record(r->in, &rec);
  if (!ok) return 0;
  *klen = static_cast<uint32_t>(rec.key.size());
  *key = static_cast<char*>(std::malloc(rec.key.size() + 1));
  std::memcpy(*key, rec.key.data(), rec.key.size());
  (*key)[rec.key.size()] = 0;
  *vlen = static_cast<uint32_t>(rec.val.size());
  *val = static_cast<char*>(std::malloc(rec.val.size() ? rec.val.size() : 1));
  if (rec.val.size()) std::memcpy(*val, rec.val.data(), rec.val.size());
  return 1;
}

SG_EXPORT int sg_recreader_count(const char* path) {
  std::ifstream in(path, std::ios::binary);
  if (!in.is_open()) return -1;
  char magic[8];
  if (!in.read(magic, 8) || std::memcmp(magic, kMagic, 8) != 0) return -1;
  int n = 0;
  Record rec;
  while (read_record(in, &rec)) ++n;
  return n;
}

SG_EXPORT void sg_recreader_seek_to_first(void* handle) {
  auto* r = static_cast<RecordReader*>(handle);
  r->stop();
  r->in.clear();
  r->in.seekg(sizeof(kMagic), std::ios::beg);
  // A reader opened with prefetching must keep prefetching across rewinds
  // (multi-epoch iteration), not silently degrade to synchronous reads.
  if (r->prefetch_depth > 0) r->start_prefetch();
}

SG_EXPORT void sg_recreader_close(void* handle) {
  delete static_cast<RecordReader*>(handle);
}

SG_EXPORT void sg_free(void* p) { std::free(p); }

// ---------------------------------------------------------------------------
// C ABI: image transforms on float32 HWC buffers
// (reference src/io/image_transformer.cc — crop/resize/flip)
// ---------------------------------------------------------------------------

SG_EXPORT int sg_image_resize_bilinear(const float* src, int h, int w, int c,
                                       float* dst, int oh, int ow) {
  if (h <= 0 || w <= 0 || oh <= 0 || ow <= 0 || c <= 0) return 0;
  const float sy = oh > 1 ? static_cast<float>(h - 1) / (oh - 1) : 0.0f;
  const float sx = ow > 1 ? static_cast<float>(w - 1) / (ow - 1) : 0.0f;
  for (int y = 0; y < oh; ++y) {
    float fy = y * sy;
    int y0 = static_cast<int>(fy);
    int y1 = y0 + 1 < h ? y0 + 1 : h - 1;
    float wy = fy - y0;
    for (int x = 0; x < ow; ++x) {
      float fx = x * sx;
      int x0 = static_cast<int>(fx);
      int x1 = x0 + 1 < w ? x0 + 1 : w - 1;
      float wx = fx - x0;
      for (int k = 0; k < c; ++k) {
        float v00 = src[(y0 * w + x0) * c + k];
        float v01 = src[(y0 * w + x1) * c + k];
        float v10 = src[(y1 * w + x0) * c + k];
        float v11 = src[(y1 * w + x1) * c + k];
        dst[(y * ow + x) * c + k] =
            v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
            v10 * wy * (1 - wx) + v11 * wy * wx;
      }
    }
  }
  return 1;
}

SG_EXPORT int sg_image_crop(const float* src, int h, int w, int c, float* dst,
                            int top, int left, int ch, int cw) {
  if (top < 0 || left < 0 || top + ch > h || left + cw > w) return 0;
  for (int y = 0; y < ch; ++y) {
    std::memcpy(dst + y * cw * c, src + ((top + y) * w + left) * c,
                sizeof(float) * cw * c);
  }
  return 1;
}

SG_EXPORT int sg_image_hflip(const float* src, int h, int w, int c,
                             float* dst) {
  for (int y = 0; y < h; ++y) {
    for (int x = 0; x < w; ++x) {
      std::memcpy(dst + (y * w + x) * c, src + (y * w + (w - 1 - x)) * c,
                  sizeof(float) * c);
    }
  }
  return 1;
}

// channel-order swap helpers: HWC <-> CHW (the reference stores CHW)
SG_EXPORT void sg_image_hwc_to_chw(const float* src, int h, int w, int c,
                                   float* dst) {
  for (int k = 0; k < c; ++k)
    for (int y = 0; y < h; ++y)
      for (int x = 0; x < w; ++x)
        dst[(k * h + y) * w + x] = src[(y * w + x) * c + k];
}

SG_EXPORT void sg_image_chw_to_hwc(const float* src, int c, int h, int w,
                                   float* dst) {
  for (int k = 0; k < c; ++k)
    for (int y = 0; y < h; ++y)
      for (int x = 0; x < w; ++x)
        dst[(y * w + x) * c + k] = src[(k * h + y) * w + x];
}

SG_EXPORT const char* sg_version() { return "singa_native 1.0"; }
